"""Greedy multi-token generation over the streaming scorer.

Reference semantics (``/root/reference/main.py:63-90``) reproduced exactly:
each of ``num_gen_token`` iterations re-runs the full sharded scoring pass on
the *current* prompts; iteration scores are concatenated along axis 1, so each
prompt accumulates ``[n_suffixes, num_gen_token, vocab]``; after every
iteration each suffix is rebuilt as the ORIGINAL suffix string plus the decode
of the argmax token history so far (greedy only — the reference's
``--temperature`` flag is commented out, ``/root/reference/main.py:47-48``).

The known scaling cliff is inherited deliberately (SURVEY.md §3.5): per-token
cost equals full-prompt cost because no KV survives between tokens — the
streaming design trades that for the tiny-HBM capability.
"""

from __future__ import annotations

import copy
from typing import Callable, Sequence

import numpy as np

Prompt = tuple[str, tuple[str, ...]]
RunFn = Callable[[list[Prompt]], list[np.ndarray]]


def generation_loop(
    run_fn: RunFn,
    prompts: Sequence[Prompt],
    num_gen_token: int,
    tokenizer,
) -> tuple[list[np.ndarray], list[Prompt]]:
    """Run ``num_gen_token`` greedy decode iterations.

    run_fn: scores the current prompts -> one ``[n_suffixes, 1, vocab]``
    float array per prompt (a single executor, or a multi-device fan-out).
    Returns (per-prompt ``[n_suffixes, num_gen_token, vocab]`` scores,
    updated prompts with generated text appended to each suffix).
    """
    original = list(prompts)
    current: list[Prompt] = copy.deepcopy(original)
    output_scores: list[np.ndarray] = []

    for i_new in range(num_gen_token):
        outputs = run_fn(current)
        if i_new == 0:
            output_scores = list(outputs)
        else:
            output_scores = [
                np.concatenate((old, new), axis=1)
                for old, new in zip(output_scores, outputs)
            ]
        # Rebuild suffixes from the ORIGINAL prompt plus the decoded argmax
        # history (/root/reference/main.py:85-90).
        for p_idx, (prefix, suffix) in enumerate(original):
            new_tokens = np.argmax(output_scores[p_idx], axis=-1)  # [S, i+1]
            current[p_idx] = (
                prefix,
                tuple(
                    s + tokenizer.decode(t) for s, t in zip(suffix, new_tokens)
                ),
            )

    return output_scores, current


__all__ = ["generation_loop"]
