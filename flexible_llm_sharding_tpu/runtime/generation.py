"""Greedy multi-token generation over the streaming scorer.

Reference semantics (``/root/reference/main.py:63-90``) reproduced exactly:
each of ``num_gen_token`` iterations re-runs the full sharded scoring pass on
the *current* prompts; iteration scores are concatenated along axis 1, so each
prompt accumulates ``[n_suffixes, num_gen_token, vocab]``; after every
iteration each suffix is rebuilt as the ORIGINAL suffix string plus the decode
of the token history so far. Decoding is greedy (argmax) by default — exact
reference behaviour — with optional temperature sampling, the flag the
reference sketched but left commented out (``/root/reference/main.py:47-48``).

The known scaling cliff is inherited deliberately (SURVEY.md §3.5): per-token
cost equals full-prompt cost because no KV survives between tokens — the
streaming design trades that for the tiny-HBM capability.
"""

from __future__ import annotations

import copy
from typing import Callable, Sequence

import numpy as np

Prompt = tuple[str, tuple[str, ...]]
RunFn = Callable[[list[Prompt]], list[np.ndarray]]


def sample_tokens(
    dist: np.ndarray,
    rng: np.random.Generator,
    temperature: float,
    top_k: int = 0,
    top_p: float = 0.0,
) -> np.ndarray:
    """Draw one token per row of ``dist`` [N, V] with the standard decoding
    controls: temperature reshaping ``p^(1/T)``, then top-k truncation
    (exactly k survivors even under ties — stable argsort breaks them by
    index, like torch.topk), renormalise, then nucleus (top-p) truncation
    (HF convention: keep the smallest sorted prefix whose mass reaches p,
    always including the most probable token). Fully vectorized — ONE
    stable argsort per row instead of per-row Python work, and one uniform
    draw per row mapped through the inverse CDF — so the host cost per
    decode step is O(N·V·log V) numpy, not a Python loop."""
    dist = np.asarray(dist, np.float64)
    logits = np.log(np.maximum(dist, 1e-30)) / max(temperature, 1e-6)
    p = np.exp(logits - logits.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    v = p.shape[-1]
    if (top_k and top_k < v) or 0.0 < top_p < 1.0:
        order = np.argsort(-p, axis=-1, kind="stable")  # [N, V]
        ranks = np.empty_like(order)
        np.put_along_axis(ranks, order, np.arange(v)[None, :], axis=-1)
        if top_k and top_k < v:
            p = np.where(ranks < top_k, p, 0.0)
            # HF order: nucleus applies to the RENORMALIZED survivor mass.
            p /= p.sum(axis=-1, keepdims=True)
        if 0.0 < top_p < 1.0:
            sorted_p = np.take_along_axis(p, order, axis=-1)
            csum = np.cumsum(sorted_p, axis=-1)
            # Keep ranks whose PRECEDING mass is < p (includes the token
            # that crosses p; zeroed top-k rejects contribute no mass).
            keep_sorted = (csum - sorted_p) < top_p
            p = np.where(np.take_along_axis(keep_sorted, ranks, axis=-1), p, 0.0)
        p /= p.sum(axis=-1, keepdims=True)
    # Inverse-CDF draw: one uniform per row. Normalize the cdf itself (as
    # rng.choice does) so float error can't leave csum[-1] = 1 - eps and a
    # tail draw select a token the filters zeroed out.
    u = rng.random(p.shape[0])
    csum = np.cumsum(p, axis=-1)
    csum /= csum[:, -1:]
    return np.minimum((csum < u[:, None]).sum(axis=-1), v - 1).astype(np.int64)


def sample_token(
    dist: np.ndarray,
    rng: np.random.Generator,
    temperature: float,
    top_k: int = 0,
    top_p: float = 0.0,
) -> int:
    """One-row convenience form of :func:`sample_tokens`."""
    return int(sample_tokens(dist[None], rng, temperature, top_k, top_p)[0])


def make_picker(cfg, rng: np.random.Generator | None = None):
    """Token selector shared by the KV-decode paths: greedy argmax when
    ``cfg.temperature`` is 0, else per-row :func:`sample_token` with the
    config's temperature/top_k/top_p (ONE rng, seeded from ``cfg.seed``,
    advanced in row-major order — deterministic per seed).

    The returned ``pick(dist, real=None)`` maps ``[..., V]`` distributions
    to ``[...]`` int tokens. ``real`` (bool, broadcast to the leading shape)
    marks rows whose token is actually consumed: padded suffix rows fall
    back to argmax WITHOUT advancing the rng, so real-token draws don't
    depend on unrelated batch composition or bucket padding."""
    if cfg.temperature <= 0:
        return lambda dist, real=None: np.argmax(dist, axis=-1)
    rng = rng if rng is not None else np.random.default_rng(cfg.seed)

    def pick(dist: np.ndarray, real=None) -> np.ndarray:
        lead = dist.shape[:-1]
        flat = dist.reshape(-1, dist.shape[-1])
        if real is None:
            return sample_tokens(
                flat, rng, cfg.temperature, cfg.top_k, cfg.top_p
            ).reshape(lead)
        # Sample only the real rows (padded rows keep an argmax placeholder
        # and never advance the rng), in row-major order for determinism.
        out = np.argmax(dist, axis=-1).reshape(-1)
        mask = np.broadcast_to(np.asarray(real, bool), lead).reshape(-1)
        if mask.any():
            out[mask] = sample_tokens(
                flat[mask], rng, cfg.temperature, cfg.top_k, cfg.top_p
            )
        return out.reshape(lead)

    return pick


def generation_loop(
    run_fn: RunFn,
    prompts: Sequence[Prompt],
    num_gen_token: int,
    tokenizer,
    temperature: float = 0.0,
    seed: int = 0,
    top_k: int = 0,
    top_p: float = 0.0,
    model_cfg=None,
    max_token_len: int = 4096,
) -> tuple[list[np.ndarray], list[Prompt]]:
    """Run ``num_gen_token`` decode iterations (greedy by default).

    run_fn: scores the current prompts -> one ``[n_suffixes, 1, vocab]``
    float array per prompt (a single executor, or a multi-device fan-out).
    Returns (per-prompt ``[n_suffixes, num_gen_token, vocab]`` scores,
    updated prompts with generated text appended to each suffix).

    ``temperature > 0`` samples each new token from ``p^(1/T)`` (renormalised)
    — the reference sketched this flag but left it commented out
    (``/root/reference/main.py:47-48``); ``0`` is exact reference (argmax)
    behaviour. ``top_k``/``top_p`` truncate the sampling distribution (only
    meaningful with temperature > 0). Sampling is deterministic given
    ``seed``.

    ``model_cfg``/``max_token_len``: REQUIRED for longrope models (Phi-3
    long-context) when callers want the upfront regime check below — pass
    the model's ``LlamaConfig`` and the SAME ``max_token_len`` the scoring
    executor tokenizes with (``cli.main`` does; the check re-tokenizes with
    a fresh ``PromptTokenizer(max_token_len)``, so a mismatched cap would
    check different lengths than the executor scores). Callers that omit
    ``model_cfg`` still fail loudly — the executor's per-pass
    ``check_longrope_regime`` backstops — but only mid-run, after weight
    streams were already spent on the completed iterations.
    """
    # longrope models (``model_cfg`` supplied): per-pass scoring re-checks
    # regime uniformity, but a multi-suffix prompt whose suffix lengths
    # DIFFER near the boundary can pass early iterations and straddle only
    # once the suffixes have grown — failing mid-run after whole weight
    # streams were spent. Reject those upfront when the growth window
    # [shortest initial length, longest initial length + num_gen_token - 1]
    # brackets the boundary. Exempt: single-suffix prompts and equal-length
    # suffix sets — each pass is a full forward, so a UNIFORM per-pass
    # table flip at the boundary is exactly HF's own recompute behaviour
    # (equal-length suffixes normally grow in lockstep; if re-tokenization
    # ever drifts them apart, the executor's per-pass check still backstops
    # with the same error).
    if (
        model_cfg is not None
        and model_cfg.rope_scaling_kind == "longrope"
        and num_gen_token > 1
    ):
        from flexible_llm_sharding_tpu.runtime.tokenization import (
            PromptTokenizer,
            check_longrope_regime,
        )

        ptok = PromptTokenizer(tokenizer, max_token_len=max_token_len)
        multi, labels = [], []
        for i, (p, s) in enumerate(prompts):
            if len(s) > 1:
                t = ptok(p, s)
                lens = t.suffix_eos[: t.num_suffixes]
                if int(lens.min()) != int(lens.max()):
                    multi.append(t)
                    labels.append(i)
        check_longrope_regime(
            model_cfg, multi, extra_len=num_gen_token - 1, labels=labels
        )

    original = list(prompts)
    current: list[Prompt] = copy.deepcopy(original)
    output_scores: list[np.ndarray] = []
    # Sampled-token history [prompt][suffix] — greedy recomputes its history
    # from argmax each iteration (exact reference semantics); sampling must
    # remember its own draws instead.
    sampled: list[list[list[int]]] = [
        [[] for _ in sfx] for _, sfx in original
    ]
    rng = np.random.default_rng(seed)

    def _pick(dist: np.ndarray) -> int:
        return sample_token(dist, rng, temperature, top_k, top_p)

    for i_new in range(num_gen_token):
        outputs = run_fn(current)
        if i_new == 0:
            output_scores = list(outputs)
        else:
            output_scores = [
                np.concatenate((old, new), axis=1)
                for old, new in zip(output_scores, outputs)
            ]
        # Rebuild suffixes from the ORIGINAL prompt plus the decoded token
        # history (/root/reference/main.py:85-90).
        for p_idx, (prefix, suffix) in enumerate(original):
            if temperature <= 0:
                history = np.argmax(output_scores[p_idx], axis=-1)  # [S, i+1]
            else:
                for s_idx in range(len(suffix)):
                    sampled[p_idx][s_idx].append(
                        _pick(output_scores[p_idx][s_idx, i_new])
                    )
                history = np.asarray(sampled[p_idx])
            current[p_idx] = (
                prefix,
                tuple(
                    s + tokenizer.decode(t) for s, t in zip(suffix, history)
                ),
            )

    return output_scores, current


__all__ = ["generation_loop", "sample_token", "sample_tokens", "make_picker"]
