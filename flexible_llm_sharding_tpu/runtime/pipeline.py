"""MP mode: the interleaved layer pipeline across the chips of a slice.

Reference semantics (``/root/reference/utils.py:151-157,189-213`` and the
``multigpu_flexibility.png`` diagram): contiguous layer shards are assigned
round-robin to devices (shard k -> device k % N), and a prompt's activations
hop device-to-device between stages. The reference coordinates this with
Python threads, a shared activation dict, a ``prompt2layer`` progress table
polled at 1-second granularity, and (in disk mode) ``.npy`` files as the
wrap-around transport from the last rank back to rank 0.

TPU-native redesign (SURVEY.md §2.3, §7):

- One host thread drives ALL stages in global execution order; there is no
  polling control plane. Pipeline concurrency is *emergent from XLA's async
  dispatch*: the host enqueues stage s+1's jitted call on chip B as soon as
  stage s's output on chip A is dispatched (not completed); the runtime
  orders them by data dependency, so chip A computes block b+1 while chip B
  computes block b — the reference's per-prompt pipelining without a single
  lock or sleep.
- Activation hops are ``jax.device_put`` of device-resident arrays —
  chip-to-chip DMA over ICI (``storage_location='tpu'``), never staged
  through host RAM the way the reference's ``.cpu()``/``.to(device)`` pairs
  are. ``cpu``/``disk`` modes keep the reference's host/disk transports
  (including the per-prompt ``.npy`` file contract for resumability).
- Weights for stage t+1 upload to *that stage's chip* while stage t computes
  (per-shard target devices in ShardWeightSource), so weight streaming and
  compute overlap across the whole pipeline.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from flexible_llm_sharding_tpu.config import FrameworkConfig, LlamaConfig
from flexible_llm_sharding_tpu.obs import trace as _trace
from flexible_llm_sharding_tpu.parallel.planner import (
    batch_ranges,
    global_stage_order,
)
from flexible_llm_sharding_tpu.runtime import resume
from flexible_llm_sharding_tpu.runtime.activations import ActivationStore
from flexible_llm_sharding_tpu.runtime.executor import (
    ScoreSink,
    ShardWeightSource,
    _DTYPES,
    finalize_scores,
    np_dtype_for,
    process_block,
)
from flexible_llm_sharding_tpu.runtime.tokenization import PromptTokenizer, make_blocks
from flexible_llm_sharding_tpu.utils import checkpoint, metrics


class PipelineRunner:
    """Drives one full scoring pass through the interleaved stage pipeline."""

    def __init__(self, cfg: FrameworkConfig, devices, tokenizer=None):
        from flexible_llm_sharding_tpu.obs.registry import (
            REGISTRY,
            weak_source,
        )

        _trace.ensure_configured(cfg)
        REGISTRY.register("pipeline", weak_source(self))
        self.cfg = cfg
        self.devices = list(devices)
        self.model_cfg = LlamaConfig.from_pretrained(cfg.model_path)
        self.dtype = _DTYPES[cfg.dtype]
        if tokenizer is None:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(cfg.model_path)
        self.tokenizer = PromptTokenizer(
            tokenizer,
            max_token_len=cfg.max_token_len,
            bucket_multiple=cfg.bucket_multiple,
        )
        self.layer_names = checkpoint.layer_names_for(
            self.model_cfg.num_hidden_layers, tie_word_embeddings=False
        )
        # (stage_idx, device_rank, layer_tuple) in execution order.
        self.stages = global_stage_order(
            len(self.layer_names), cfg.layer_num_per_shard, len(self.devices)
        )
        self.stats: dict[str, float] = {}
        self._use_pallas = cfg.pallas_enabled()
        # Per-stage dispatch events; ``dispatch_wall_s`` vs ``total_wall_s``
        # in stats is the pipelining evidence — see _run_batch.
        self.recorder = metrics.Recorder(verbose=cfg.verbose_metrics)
        # Model-content pin for resume (mirrors StreamingExecutor): the
        # manifest digest rides in the workload signature and the progress
        # marker, so a resumed pipeline never consumes inter-stage spills
        # produced against different weights.
        from flexible_llm_sharding_tpu.integrity import manifest as _iman

        self._manifest_digest = _iman.manifest_digest(
            _iman.load_manifest(cfg.model_path) if cfg.verify_weights else None
        )

    @property
    def _np_dtype(self):
        return np_dtype_for(self.cfg.dtype)

    def __call__(self, prompts) -> list[np.ndarray]:
        out: list[np.ndarray] = []
        for i, (lo, hi) in enumerate(batch_ranges(len(prompts), self.cfg.num_batch)):
            out += self._run_batch(prompts[lo:hi], batch=i)
        return out

    # -- disk-mode crash resume (MP counterpart of the executor's) ---------
    # In disk mode every inter-stage handoff is a durable per-prompt .npy
    # pair (generation ping-pong: see ActivationStore.set_shard), so a
    # crashed pipeline restarts from the last fully-stored stage — even a
    # mid-stage crash, whose partial writes went to the OTHER generation.
    # The signature (runtime/resume.py) guards against resuming into a
    # different checkpoint, workload, stage plan, or device count (rank
    # assignment is part of the stage tuples).

    def _resume_signature(self, toks) -> str:
        return resume.workload_signature(
            toks,
            ("mp", [(r, s) for (_, r, s) in self.stages]),
            self.cfg.model_path,
            self.cfg.dtype,
            self.cfg.block_size,
            manifest_digest=self._manifest_digest,
        )

    def _marker_path(self, sig: str, tag: str) -> str:
        return resume.marker_path(self.cfg.disk_folder, sig, tag)

    def _resume_start(self, sig: str, tag: str, last_real: int) -> int:
        if not (self.cfg.resume and self.cfg.storage_location == "disk"):
            return 0
        data = resume.read_marker(
            self._marker_path(sig, tag), sig,
            manifest_hash=self._manifest_digest,
        )
        # The head stage produces the scores and is never marked complete.
        return min(int(data.get("completed_stages", 0)), last_real)

    def _mark_stage(self, sig: str, tag: str, done: int) -> None:
        resume.write_marker(
            self._marker_path(sig, tag), sig, completed_stages=done,
            manifest_hash=self._manifest_digest,
        )

    def _run_batch(self, prompts, batch: int = 0) -> list[np.ndarray]:
        t_start = time.perf_counter()
        toks = [self.tokenizer(p, s) for p, s in prompts]
        blocks = make_blocks(toks, self.cfg.block_size)
        store = ActivationStore(
            self.cfg.storage_location,
            self.cfg.disk_folder,
            max_in_cpu=self.cfg.max_activation_in_cpu,
            np_dtype=self._np_dtype,
            batch=batch,
            # Spill writes retry ENOSPC under the run's policy (typed
            # DiskFullError on exhaustion) — same contract as the
            # single-device executor's store.
            retry_policy=self.cfg.retry_policy(),
        )
        resumable = self.cfg.storage_location == "disk"
        last_real = max(
            (i for i, (_, _, s) in enumerate(self.stages) if s), default=0
        )
        sig = self._resume_signature(toks) if resumable else ""
        start_stage = (
            self._resume_start(sig, store.tag, last_real) if resumable else 0
        )
        stage_shards = [s for (_, _, s) in self.stages[start_stage:]]
        stage_devs = [self.devices[r] for (_, r, _) in self.stages[start_stage:]]
        from flexible_llm_sharding_tpu.faults.inject import FaultInjector
        from flexible_llm_sharding_tpu.runtime import hostcache, residency

        # Partial residency over the pipeline: a pinned layer stays on its
        # STAGE's chip (ensure_pinned runs per (shard, stage device) pair
        # inside the source), so each stage's sweep skips its own pins.
        tier = residency.tier_for(
            self.cfg,
            self.layer_names,
            self.model_cfg.tie_word_embeddings,
            self.devices[0],
        )
        source = ShardWeightSource(
            self.cfg.model_path,
            self.layer_names,
            stage_shards,
            self._np_dtype,
            devices=stage_devs,
            prefetch_depth=self.cfg.effective_prefetch_depth(),
            tied_embeddings=self.model_cfg.tie_word_embeddings,
            layer_sliding=self.model_cfg.layer_sliding,
            layer_rope=self.model_cfg.layer_rope,
            retry_policy=self.cfg.retry_policy(),
            injector=FaultInjector.from_config(self.cfg.faults),
            verify_weights=self.cfg.verify_weights,
            host_cache=hostcache.cache_for(self.cfg),
            readahead_threads=self.cfg.readahead_threads,
            residency=tier,
        )

        n_layers = len(self.layer_names)
        scores: dict[int, np.ndarray] = ScoreSink(
            max_device=self.cfg.score_sink_max_device
        )
        # Block metadata is uploaded per device on first use (jit operands
        # must be colocated with that stage's weights).
        host_meta = {
            b: (
                np.stack([toks[i].prefix_ids for i in idxs]),
                np.stack([toks[i].suffix_ids for i in idxs]),
                np.array([toks[i].prefix_len for i in idxs], dtype=np.int32),
                np.stack([toks[i].suffix_eos for i in idxs]),
            )
            for b, idxs in enumerate(blocks)
        }
        dev_meta: dict[tuple[int, int], tuple] = {}

        def meta_on(b: int, dev) -> tuple:
            key = (b, id(dev))
            if key not in dev_meta:
                dev_meta[key] = tuple(
                    jax.device_put(a, dev) for a in host_meta[b]
                )
            return dev_meta[key]

        bar = metrics.progress_bar(
            (len(self.stages) - start_stage) * max(len(blocks), 1),
            desc="pipeline",
            unit="blk",
        )
        try:
            for ((stage_idx, rank, layer_idxs), (_, segments)) in zip(
                self.stages[start_stage:], source
            ):
                if not layer_idxs:  # round-up padding stage
                    bar.update(max(len(blocks), 1))
                    continue
                store.set_shard(stage_idx)
                dev = self.devices[rank]
                t_stage = time.perf_counter()
                with _trace.span(
                    "pipeline_stage", cat="pipeline", stage=stage_idx,
                    rank=rank,
                ):
                    for b, idxs in enumerate(blocks):
                        process_block(
                            self.model_cfg,
                            self.dtype,
                            segments,
                            layer_idxs,
                            n_layers,
                            store,
                            b,
                            idxs,
                            meta_on(b, dev),
                            dev,
                            toks,
                            scores,
                            use_pallas=self._use_pallas,
                        )
                        bar.update(1)
                self.recorder.record(
                    "stage_dispatch",
                    time.perf_counter() - t_stage,
                    stage=stage_idx,
                    rank=rank,
                )
                if resumable and stage_idx < last_real:
                    # Durable-store barrier, then advance the marker; disk
                    # mode is already file-synchronized stage-to-stage, so
                    # this flush costs nothing extra.
                    store.flush()
                    self._mark_stage(sig, store.tag, stage_idx + 1)
        except BaseException:
            # Same hazard as StreamingExecutor's error path: a leaked async
            # disk writer would pin queued device arrays in HBM.
            try:
                store.clear()
            except Exception:  # flscheck: disable=EXC-TAXONOMY: best-effort cleanup on the error path; the stream exception re-raised below is the root cause and must not be masked
                pass  # the stream exception is the root cause; keep it
            raise
        finally:
            bar.close()
            source.close()
        # All stages are now DISPATCHED; nothing above host-synced (tpu
        # storage: activation hops are device-to-device, head scores copy
        # back asynchronously). dispatch_wall << total_wall is the evidence
        # that the driver ran ahead of the chips — XLA executes each chip's
        # queue independently, so stage s+1 on chip B overlaps stage s on
        # chip A exactly as the reference's emergent per-prompt pipelining
        # does (/root/reference/utils.py:185-213), with zero polling.
        dispatch_wall = time.perf_counter() - t_start
        finalize_scores(scores)
        if resumable:  # completed: drop the marker
            resume.remove_marker(self._marker_path(sig, store.tag))

        self.stats = {
            "load_weights_time_s": source.load_time,
            "dispatch_wall_s": dispatch_wall,
            "total_wall_s": time.perf_counter() - t_start,
            "num_stages": float(len(self.stages)),
            "tokens_processed": float(sum(t.tokens_processed for t in toks)),
        }
        if tier is not None:
            rs = tier.stats()
            # Process-wide gauge (per-stage pins sum across the chips).
            self.stats["pinned_bytes"] = float(rs["pinned_bytes"])
        store.clear()
        return [scores[i] for i in range(len(prompts))]


def run_pipeline(
    cfg: FrameworkConfig, prompts, devices, tokenizer=None
) -> list[np.ndarray]:
    return PipelineRunner(cfg, devices, tokenizer=tokenizer)(list(prompts))


__all__ = ["PipelineRunner", "run_pipeline"]
