"""Typed configuration objects.

The reference threads a raw argparse ``args`` namespace everywhere
(``/root/reference/utils.py:33,80``) with 10 flags (``/root/reference/main.py:30-49``)
and a module-level ``max_token_len = 4096`` constant (``/root/reference/utils.py:14``).
Here the same flag surface becomes a small frozen dataclass, plus a model config
read from a HuggingFace ``config.json``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

# The reference's hard sequence cap (/root/reference/utils.py:14). Kept as the
# default, but configurable here instead of a module constant.
DEFAULT_MAX_TOKEN_LEN = 4096

# MLP gate activations models/llama.py implements (its _ACT table asserts it
# stays in sync with this set).
SUPPORTED_ACTIVATIONS = frozenset({"silu", "gelu", "gelu_pytorch_tanh"})

# Named fault-injection sites (faults/inject.py fires these; config
# validation and the --chaos CLI flag key off this tuple so a typo'd site
# fails loudly instead of silently injecting nothing). Machine-checked by
# flscheck's SITE-REG rule (analysis/rules.py): every literal fired in the
# package must be registered here AND documented in docs/faults.md's site
# table, and every entry here must actually be fired somewhere. The corrupt_* sites
# are SILENT-corruption sites: instead of raising, they bit-flip (or
# truncate) the bytes mid-flight — what the integrity layer's checksums
# exist to catch (corrupt_shard: one layer file's loaded tensors;
# corrupt_activation: one .npy spill read). The replica_* sites are
# REPLICA-level (serve/fleet.py, fired once per shard step of every
# replica's sweep): replica_kill crashes a whole serving engine mid-sweep
# (engine-fatal, modeling a dead replica process), replica_stall wedges
# its thread until the fleet's liveness check declares it dead — both
# exist to prove the router's hard-fail + exactly-once re-dispatch path.
# The RESOURCE-PRESSURE sites model the three exhaustion paths the
# architecture leans on hardest (runtime/pressure.py, docs/pressure.md):
# host_oom raises MemoryError inside a host shard build (typed to
# HostOOMError and retried like any transient I/O blip), disk_full raises
# ENOSPC inside an activation-spill write (typed DiskFullError, same
# retry ladder), link_throttle stalls a host->HBM put for latency_s —
# a saturated link slows, it never errors.
FAULT_SITES = (
    "shard_read",
    "device_put",
    "engine_step",
    "queue_admission",
    "corrupt_shard",
    "corrupt_activation",
    "replica_kill",
    "replica_stall",
    "host_oom",
    "disk_full",
    "link_throttle",
)


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Deterministic fault injection (faults/inject.py). Off by default;
    enabled by the chaos tests and the ``--chaos`` CLI flag.

    Rates partition one uniform draw per site fire: with probability
    ``error_rate`` an IOError is raised, ``truncate_rate`` a simulated
    truncated read, ``latency_rate`` a ``latency_s`` sleep; otherwise the
    fire is clean. The schedule is a pure function of ``(seed, site,
    per-site call count)`` — reproducible across runs, platforms, and
    thread interleavings."""

    enabled: bool = False
    seed: int = 0
    error_rate: float = 0.0
    truncate_rate: float = 0.0
    latency_rate: float = 0.0
    latency_s: float = 0.01
    sites: tuple[str, ...] = ()  # () = every site
    # Total faults injected before the schedule goes permanently clean
    # (-1 = unlimited). Models a transient outage that ENDS — lets a test
    # force exactly one retry-exhaustion and then assert clean recovery.
    max_faults: int = -1

    def __post_init__(self) -> None:
        for name in ("error_rate", "truncate_rate", "latency_rate"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        # 1e-9 slack: a legal decimal partition like 0.1+0.2+0.7 sums to
        # 1.0000000000000002 in IEEE-754 and must not be rejected.
        if self.error_rate + self.truncate_rate + self.latency_rate > 1.0 + 1e-9:
            raise ValueError("fault rates must sum to <= 1")
        if self.latency_s < 0:
            raise ValueError("latency_s must be >= 0")
        unknown = set(self.sites) - set(FAULT_SITES)
        if unknown:
            raise ValueError(
                f"unknown fault sites {sorted(unknown)} (one of {FAULT_SITES})"
            )
        object.__setattr__(self, "sites", tuple(self.sites))


@dataclasses.dataclass(frozen=True)
class PressureConfig:
    """Resource-pressure brownout controller (runtime/pressure.py). Off by
    default; enabled by ``--pressure`` on both CLIs.

    A ``PressureMonitor`` samples host MemAvailable, spill-disk free
    bytes, HBM headroom, and the host->HBM link rate every ``poll_s``;
    when a threshold trips (or a hard resource failure — a real or
    injected ``host_oom``/``disk_full`` event — is observed), the
    ``BrownoutController`` walks an ordered, REVERSIBLE degradation
    ladder: shrink the host shard cache, evict residency pins back to
    streaming, shed new admissions with a typed ``Overloaded`` rejection
    (carrying ``shed_retry_after_s`` as the retry hint), and drain fleet
    replicas — then steps back down once ``step_down_polls`` consecutive
    polls come back clean. Thresholds set to 0 disable that signal
    (events still drive the ladder)."""

    enabled: bool = False
    poll_s: float = 1.0
    # Signal thresholds (0 = that signal off; unknown samples never trip).
    host_min_gb: float = 1.0      # MemAvailable floor
    disk_min_gb: float = 1.0      # spill-disk (disk_folder) free-bytes floor
    hbm_headroom_frac: float = 0.05  # device free/limit floor
    link_min_gbps: float = 0.0    # host->HBM streamed-bytes rate floor
    # Ladder behavior.
    cache_shrink_frac: float = 0.5   # level-1 host-cache budget multiplier
    shed_retry_after_s: float = 1.0  # Overloaded.retry_after_s hint
    step_down_polls: int = 3         # consecutive clean polls per step down

    def __post_init__(self) -> None:
        if self.poll_s <= 0:
            raise ValueError("poll_s must be > 0")
        for name in ("host_min_gb", "disk_min_gb", "link_min_gbps",
                     "shed_retry_after_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        for name in ("hbm_headroom_frac", "cache_shrink_frac"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        if self.step_down_polls < 1:
            raise ValueError("step_down_polls must be >= 1")


@dataclasses.dataclass(frozen=True)
class AdapterConfig:
    """Multi-tenant LoRA adapter serving (adapters/; docs/adapters.md).

    ``dir`` is a directory of named adapters — one subdirectory per
    adapter, each holding per-layer safetensors delta factors plus an
    ``adapter_plan.json`` (the PR 14 plan shape) and an integrity
    manifest. Empty (default) disables the subsystem entirely: requests
    carrying an ``adapter_id`` are rejected and the sweep math is
    byte-identical to a tree without adapters. ``max_gb`` budgets the
    host-resident adapter LRU (``adapters/loader.py``): an explicit
    number of GB, or None (auto) for a small fraction of available RAM —
    auto stays ON under fault injection (chaos-exempt like the KV pool:
    the chaos smoke serves adapters *under* faults, so the budget must
    not silently vanish there)."""

    dir: str = ""
    max_gb: float | None = None

    def __post_init__(self) -> None:
        if self.max_gb is not None and self.max_gb < 0:
            raise ValueError(
                f"max_gb must be >= 0 (or None for auto), got {self.max_gb}"
            )


# Multimodal wrapper model types -> their language-model type. Published
# Gemma-3 / Llama-4 checkpoints are vision+text bundles whose config nests
# the text model under "text_config"; both the config parse and the
# checkpoint splitter derive the text model through extract_text_config —
# ONE rule, so the two can't drift.
MULTIMODAL_TEXT_TYPES = {"gemma3": "gemma3_text", "llama4": "llama4_text"}


def extract_text_config(d: dict) -> dict | None:
    """The normalized language-model config dict of a multimodal wrapper
    config, or None when ``d`` is not a wrapper. Raises ValueError for a
    wrapper with no text_config."""
    text_type = MULTIMODAL_TEXT_TYPES.get(d.get("model_type"))
    if text_type is None:
        return None
    tc = d.get("text_config")
    if not tc:
        raise ValueError(
            f"{d.get('model_type')} config without text_config — cannot "
            "derive the language model"
        )
    tc = dict(tc)
    tc.setdefault("model_type", text_type)
    return tc

# Fields copied by name from ANY foreign HF config.json — they mean the same
# thing across the supported families. Everything else is family-gated below
# (see from_hf_config's stray-key defence).
_UNIVERSAL_HF_FIELDS = frozenset({
    "model_type", "vocab_size", "hidden_size", "intermediate_size",
    "num_hidden_layers", "num_attention_heads", "num_key_value_heads",
    "rms_norm_eps", "rope_theta", "max_position_embeddings",
    "tie_word_embeddings", "hidden_act", "mlp_bias",
})

# Extra fields a foreign config.json may contribute, per declared model_type
# (these are real HF config attributes for that family; the family branch
# supplies the defaults when absent).
_FAMILY_HF_FIELDS: dict[str, frozenset[str]] = {
    "mistral": frozenset({"sliding_window"}),
    "qwen2": frozenset({"sliding_window"}),
    "qwen3": frozenset({"sliding_window"}),
    "qwen3_moe": frozenset(
        {"sliding_window", "num_local_experts", "num_experts_per_tok"}
    ),
    "mixtral": frozenset(
        {"sliding_window", "num_local_experts", "num_experts_per_tok"}
    ),
    "phi3": frozenset({"sliding_window"}),
    "gemma2": frozenset({"query_pre_attn_scalar", "sliding_window"}),
    "gemma3_text": frozenset(
        {"query_pre_attn_scalar", "sliding_window", "rope_local_theta"}
    ),
    "llama4_text": frozenset(
        {
            "num_local_experts",
            "num_experts_per_tok",
            "attention_chunk_size",
            "intermediate_size_mlp",
            "attn_temperature_tuning",
        }
    ),
    "deepseek_v3": frozenset(
        {
            "kv_lora_rank",
            "q_lora_rank",
            "qk_nope_head_dim",
            "qk_rope_head_dim",
            "v_head_dim",
            "num_experts_per_tok",
        }
    ),
}


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Model hyperparameters, mirroring the fields of a HF config.json.

    Covers the Llama *family* of decoder architectures: Llama-1/2/3 (the
    reference's only model, ``/root/reference/utils.py:101,110``), plus the
    Llama-shaped variants the same streaming machinery runs unchanged —
    Mistral (sliding-window attention) and Qwen2 (biased Q/K/V projections).
    The family differences are data, not code paths: bias flags and an
    optional attention window, all static jit args.
    """

    # 'llama' | 'mistral' | 'qwen2' | 'qwen3' | 'mixtral' | 'gemma'
    model_type: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    explicit_head_dim: int | None = None  # HF 'head_dim' when != hidden/heads
    # Projection biases. Llama's HF config drives all four attention
    # projections from one 'attention_bias' flag; Qwen2 hard-codes bias on
    # q/k/v but none on o_proj, hence the split here.
    attention_in_bias: bool = False  # bias on wq/wk/wv
    attention_out_bias: bool = False  # bias on wo
    mlp_bias: bool = False  # bias on gate/up/down
    # Sliding-window attention (Mistral; Qwen2 with use_sliding_window).
    # None = full causal. Semantics match HF masking_utils: query i attends
    # key j iff j <= i and i - j < sliding_window.
    sliding_window: int | None = None
    # Mixture-of-experts MLP (Mixtral / Qwen3-MoE). 0 = dense. Routing
    # matches HF: softmax over all experts (fp32) -> top-k -> renormalise
    # (iff moe_norm_topk_prob; HF calls it norm_topk_prob and it is the
    # ONLY difference between the Mixtral and Qwen3-MoE blocks) -> combine.
    num_local_experts: int = 0
    num_experts_per_tok: int = 2
    moe_norm_topk_prob: bool = True
    # Per-head-dim RMSNorm on q/k after the head reshape, before RoPE
    # (Qwen3; HF: 'unlike olmo, only on the head dim').
    qk_norm: bool = False
    # MLP gate activation. 'silu' (llama/mistral/qwen/mixtral),
    # 'gelu_pytorch_tanh' (gemma), 'gelu' (exact erf).
    hidden_act: str = "silu"
    # Gemma conventions: RMSNorm multiplies by (1 + weight) IN FLOAT32
    # before the downcast (HF PR #29402 — the cast order is quality-
    # relevant at bf16), and embeddings are scaled by sqrt(hidden_size)
    # (the normalizer itself rounded to the compute dtype, per HF).
    norm_unit_offset: bool = False
    embed_scale: bool = False
    # Gemma2 additions. ffw_sandwich_norms: post_attention_layernorm moves
    # to the attention OUTPUT (before the residual add) and the MLP gets
    # pre/post_feedforward_layernorms. Softcaps apply soft*tanh(x/soft) to
    # attention scores (pre-mask) / final logits. query_pre_attn_scalar
    # replaces head_dim in the attention scale when set. layer_sliding
    # toggles the sliding window PER LAYER (True = sliding) — Gemma2
    # alternates, layer_types-derived; None = uniform per sliding_window.
    ffw_sandwich_norms: bool = False
    attn_logit_softcap: float | None = None
    final_logit_softcap: float | None = None
    query_pre_attn_scalar: float | None = None
    layer_sliding: tuple[bool, ...] | None = None
    # Gemma3: sliding (local) layers use this UNSCALED rope base while full
    # (global) layers use rope_theta + rope_scaling. None = single base.
    rope_local_theta: float | None = None
    # Llama4 additions. Chunked attention: local layers (layer_sliding=True)
    # attend within position chunks of this size instead of a sliding
    # window (mutually exclusive with sliding_window). layer_rope: per-layer
    # rope on/off (NoPE global layers). qk_l2_norm: weightless L2 norm on
    # q/k AFTER rope, rope layers only. attn_temperature_tuning: NoPE-layer
    # queries scale by log(floor((pos+1)/floor)+1)*coef + 1. moe_layer
    # pattern: True = that layer's MLP is the (shared + routed top-k
    # sigmoid-input-scaled) MoE; dense llama4 layers use
    # intermediate_size_mlp.
    attention_chunk_size: int | None = None
    layer_rope: tuple[bool, ...] | None = None
    rope_interleaved: bool = False  # llama4 complex-pair rotation
    qk_l2_norm: bool = False
    attn_temperature_tuning: bool = False
    attn_floor_scale: float = 8192.0
    attn_scale_coef: float = 0.1
    # Descriptive round-trip metadata: the runtime derives MoE-vs-dense
    # structure and the dense width from the checkpoint's weight keys/shapes
    # (the files are ground truth); these record the pattern for tooling.
    moe_layer_pattern: tuple[bool, ...] | None = None
    intermediate_size_mlp: int | None = None

    def __post_init__(self) -> None:
        if self.sliding_window is not None and self.attention_chunk_size is not None:
            # The attention ops implement exactly one local form per model;
            # both set would make the monolithic and streaming paths mask
            # differently instead of failing loudly.
            raise ValueError(
                "sliding_window and attention_chunk_size are mutually exclusive"
            )

    @property
    def attn_scale(self) -> float:
        base = (
            self.query_pre_attn_scalar
            if self.query_pre_attn_scalar is not None
            else self.head_dim
        )
        return float(base) ** -0.5
    # RoPE scaling, flattened to hashable fields (the config must stay a
    # frozen/hashable jit static arg): kind None = unscaled, or
    # 'linear' (Llama-2 long) / 'llama3' (Llama-3.1+ frequency bands) /
    # 'yarn' (NTK-by-parts: Qwen2.5-long / DeepSeek-style checkpoints).
    rope_scaling_kind: str | None = None
    rope_scaling_factor: float = 1.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    # yarn-only: ramp boundaries, the cos/sin attention factor (resolved at
    # parse time from attention_factor / mscale / mscale_all_dim / factor),
    # and whether the correction range truncates to whole dims (HF default).
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    rope_attention_factor: float = 1.0
    rope_truncate: bool = True
    # longrope-only (Phi-3 long-context): per-frequency-band extension
    # factors, head_dim//2 entries each. The long/short choice is made at
    # runtime from the sequence's real length vs rope_original_max_position
    # (ops/rope.py rope_cos_sin).
    rope_long_factor: tuple | None = None
    rope_short_factor: tuple | None = None
    # Multi-head latent attention (DeepSeek-V2/V3, model_type deepseek_v3).
    # kv_lora_rank > 0 switches the q/k/v assembly (models/llama.py
    # _qkv_mla): queries optionally LoRA'd (q_lora_rank; None = dense
    # q_proj), KV compressed to kv_lora_rank + one SHARED qk_rope_head_dim
    # rope key, decompressed per head to qk_nope_head_dim keys and
    # v_head_dim values. head_dim (qk) = qk_nope + qk_rope; values keep
    # their own v_head_dim.
    kv_lora_rank: int = 0
    q_lora_rank: int | None = None
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int | None = None
    # DeepSeek MoE routing deltas vs Mixtral (models/llama.py
    # _deepseek_moe_mlp): sigmoid scores, selection biased by a trained
    # correction buffer (weights stay unbiased), group-limited top-k
    # (n_group groups scored by their top-2 sum, best topk_group groups
    # kept), x routed_scaling_factor, plus a shared expert of
    # n_shared_experts x the routed width.
    moe_n_group: int = 1
    moe_topk_group: int = 1
    moe_routed_scaling_factor: float = 1.0
    # DeepSeek shared-expert width multiplier: the shared expert is ONE MLP
    # of n_shared_experts x the routed width (V3: 1; V2/V2-Lite: 2). Forward
    # passes take the width from the checkpoint's own shapes; this field
    # keeps the analytic param/FLOPs accounting (utils/metrics.py) and
    # init_mixed_params consistent with it.
    n_shared_experts: int = 1

    @property
    def head_dim(self) -> int:
        if self.kv_lora_rank:  # MLA: the qk head dim
            return self.qk_nope_head_dim + self.qk_rope_head_dim
        if self.explicit_head_dim is not None:
            return self.explicit_head_dim
        return self.hidden_size // self.num_attention_heads

    @property
    def v_dim(self) -> int:
        """Value head dim — equals head_dim except under MLA."""
        return self.v_head_dim if self.v_head_dim is not None else self.head_dim

    @property
    def rope_scaling_spec(self) -> tuple | None:
        """Hashable spec consumed by ops.rope.rope_cos_sin."""
        if self.rope_scaling_kind is None:
            return None
        if self.rope_scaling_kind == "linear":
            return ("linear", self.rope_scaling_factor)
        if self.rope_scaling_kind == "yarn":
            return (
                "yarn",
                self.rope_scaling_factor,
                self.rope_beta_fast,
                self.rope_beta_slow,
                self.rope_original_max_position,
                self.rope_attention_factor,
                self.rope_truncate,
            )
        if self.rope_scaling_kind == "longrope":
            return (
                "longrope",
                self.rope_long_factor,
                self.rope_short_factor,
                self.rope_original_max_position,
                self.rope_attention_factor,
            )
        return (
            "llama3",
            self.rope_scaling_factor,
            self.rope_low_freq_factor,
            self.rope_high_freq_factor,
            self.rope_original_max_position,
        )

    @staticmethod
    def _sliding_pattern(
        d: dict[str, Any], family: str, default_fn, token: str = "sliding_attention"
    ) -> tuple[bool, ...]:
        """Per-layer local-attention flags from ``layer_types`` (validated
        against num_hidden_layers) or the family's derivation rule
        ``default_fn(i, n)``. ``token`` is the layer_types value meaning
        "local" (llama4 uses 'chunked_attention')."""
        # 32 = this dataclass's num_hidden_layers default, so a derived
        # pattern always matches the constructed config's layer count.
        n = d.get("num_hidden_layers", 32)
        lt = d.get("layer_types")
        pattern = (
            tuple(t == token for t in lt)
            if lt
            else tuple(bool(default_fn(i, n)) for i in range(n))
        )
        if len(pattern) != n:
            raise ValueError(
                f"{family} layer_types has {len(pattern)} entries for {n} layers"
            )
        return pattern

    @classmethod
    def _apply_sliding_pattern(
        cls, kwargs: dict[str, Any], d: dict[str, Any], family: str, default_fn,
        default_window: int,
    ) -> None:
        """Fold a per-layer pattern into (sliding_window, layer_sliding):
        all-off -> window None; all-on -> uniform window; mixed -> flags.
        An explicit native layer_sliding key wins untouched."""
        if "layer_sliding" in kwargs:
            return
        pattern = cls._sliding_pattern(d, family, default_fn)
        kwargs.setdefault("sliding_window", default_window)
        if not any(pattern):
            kwargs["sliding_window"] = None
        elif not all(pattern):
            kwargs["layer_sliding"] = pattern

    @classmethod
    def _apply_qwen_window(cls, kwargs: dict[str, Any], d: dict[str, Any]) -> None:
        """HF qwen2/qwen3: window active only under use_sliding_window; layer
        i slides iff i >= max_window_layers (class default 28), or per the
        explicit layer_types list. Both HF config classes default
        sliding_window to 4096."""
        if "layer_sliding" in kwargs:  # explicit native key wins
            return
        if not d.get("use_sliding_window", False):
            kwargs["sliding_window"] = None
            return
        mwl = d.get("max_window_layers", 28)
        cls._apply_sliding_pattern(kwargs, d, "qwen", lambda i, n: i >= mwl, 4096)

    @classmethod
    def from_hf_config(cls, d: dict[str, Any]) -> "LlamaConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        model_type = d.get("model_type", "llama")
        # Configs this framework saved itself (save_params marks them) carry
        # every native field explicitly and round-trip by field name. A
        # FOREIGN config.json only contributes fields that mean the same
        # thing for its declared model_type: a stray numerics-changing key
        # in a merged/"llamafied" export (qk_norm, attention_chunk_size,
        # layer_sliding, softcaps, ...) must be ignored, not silently
        # honoured — the family branches below re-derive those from the HF
        # names instead.
        # Migration: configs saved by earlier framework versions predate the
        # marker but always wrote native-only field names (attention_in_bias
        # is unconditional in save_params) — no foreign HF export carries it.
        native = bool(d.get("fls_native")) or "attention_in_bias" in d
        if native:
            kwargs = {k: v for k, v in d.items() if k in known}
        else:
            allowed = _UNIVERSAL_HF_FIELDS | _FAMILY_HF_FIELDS.get(
                model_type, frozenset()
            )
            kwargs = {k: v for k, v in d.items() if k in known and k in allowed}
        # Family-specific conventions (numerics-changing features either map
        # to a native field here or fail loudly — never silently drop).
        if model_type in ("llama", ""):
            if d.get("attention_bias"):  # HF Llama: one flag, all four projs
                kwargs.setdefault("attention_in_bias", True)
                kwargs.setdefault("attention_out_bias", True)
            # HF LlamaModel ignores a stray sliding_window key (common in
            # llamafied/merged exports); honouring it here would silently
            # change logits vs HF.
            kwargs["sliding_window"] = None
        elif model_type == "qwen2":
            # HF Qwen2 hard-codes bias=True on q/k/v, False on o_proj.
            kwargs.setdefault("attention_in_bias", True)
            kwargs.setdefault("attention_out_bias", False)
            cls._apply_qwen_window(kwargs, d)
        elif model_type in ("qwen3", "qwen3_moe"):
            # One attention_bias flag for all four projections (like Llama,
            # default False) + per-head-dim q/k RMSNorm.
            if d.get("attention_bias"):
                kwargs.setdefault("attention_in_bias", True)
                kwargs.setdefault("attention_out_bias", True)
            kwargs.setdefault("qk_norm", True)
            cls._apply_qwen_window(kwargs, d)
            if model_type == "qwen3":
                # Dense Qwen3Config's class default; Qwen3MoeConfig has NO
                # head_dim attribute (falls back to hidden/heads), so the
                # MoE branch must not invent one.
                kwargs.setdefault("explicit_head_dim", 128)
            if model_type == "qwen3_moe":
                if not d.get("num_experts") and not d.get("num_local_experts"):
                    raise ValueError("qwen3_moe config without num_experts")
                kwargs.setdefault("num_local_experts", d.get("num_experts", 0))
                kwargs.setdefault("num_experts_per_tok", d.get("num_experts_per_tok", 8))
                kwargs.setdefault("moe_norm_topk_prob", d.get("norm_topk_prob", False))
                # Dense layers (mlp_only_layers / decoder_sparse_step) are a
                # checkpoint-structure fact; record the pattern as metadata.
                step = d.get("decoder_sparse_step", 1)
                only = set(d.get("mlp_only_layers") or [])
                n = d.get("num_hidden_layers", 32)  # match the dataclass default
                pattern = tuple(
                    i not in only and (i + 1) % step == 0 for i in range(n)
                )
                if not all(pattern):
                    kwargs.setdefault("moe_layer_pattern", pattern)
        elif model_type == "gemma":
            kwargs.setdefault("norm_unit_offset", True)
            kwargs.setdefault("embed_scale", True)
            # GemmaConfig's class defaults (tie=True, head_dim=256) are
            # OMITTED from config.json by HF's to_diff_dict exactly when the
            # checkpoint uses them; our dataclass defaults differ, so apply
            # the family defaults here (explicit values still win).
            kwargs.setdefault("tie_word_embeddings", True)
            kwargs.setdefault("explicit_head_dim", 256)
            # HF GemmaMLP IGNORES the legacy hidden_act key entirely: when
            # hidden_activation is None it forces gelu_pytorch_tanh (the
            # original google/gemma config.json ships hidden_act='gelu' and
            # HF still runs the tanh approximation). Only a native config's
            # explicit hidden_act wins.
            if not native:
                kwargs["hidden_act"] = (
                    d.get("hidden_activation") or "gelu_pytorch_tanh"
                )
            kwargs["sliding_window"] = None
        elif model_type == "gemma2":
            kwargs.setdefault("norm_unit_offset", True)
            kwargs.setdefault("embed_scale", True)
            kwargs.setdefault("tie_word_embeddings", True)
            kwargs.setdefault("explicit_head_dim", 256)  # Gemma2Config default
            if not native:  # HF Gemma*MLP ignores the legacy hidden_act key
                kwargs["hidden_act"] = (
                    d.get("hidden_activation") or "gelu_pytorch_tanh"
                )
            kwargs["ffw_sandwich_norms"] = True
            # setdefault: explicit NATIVE keys (our own saved configs,
            # including explicit nulls) win over the HF names/defaults.
            kwargs.setdefault("attn_logit_softcap", d.get("attn_logit_softcapping", 50.0))
            kwargs.setdefault("final_logit_softcap", d.get("final_logit_softcapping", 30.0))
            kwargs.setdefault("query_pre_attn_scalar", 256)
            # Alternating local/global attention (HF default: every even
            # layer slides).
            cls._apply_sliding_pattern(
                kwargs, d, "gemma2", lambda i, n: (i + 1) % 2, 4096
            )
        elif model_type == "gemma3_text":
            kwargs.setdefault("norm_unit_offset", True)
            kwargs.setdefault("embed_scale", True)
            kwargs.setdefault("tie_word_embeddings", True)
            kwargs.setdefault("explicit_head_dim", 256)
            kwargs.setdefault("qk_norm", True)  # Gemma3RMSNorm, (1+w) style
            if not native:  # HF Gemma*MLP ignores the legacy hidden_act key
                kwargs["hidden_act"] = (
                    d.get("hidden_activation") or "gelu_pytorch_tanh"
                )
            kwargs["ffw_sandwich_norms"] = True
            kwargs.setdefault("query_pre_attn_scalar", d.get("query_pre_attn_scalar", 256))
            kwargs.setdefault("rope_theta", 1_000_000.0)  # global layers
            kwargs.setdefault("rope_local_theta", d.get("rope_local_base_freq", 10_000.0))
            # 5:1 local/global: every 6th layer is full attention.
            cls._apply_sliding_pattern(
                kwargs, d, "gemma3", lambda i, n: (i + 1) % 6 != 0, 4096
            )
        elif model_type == "gemma3":
            # Multimodal wrapper config: the language model is the nested
            # text_config (the splitter extracts its weights the same way).
            return cls.from_hf_config(extract_text_config(d))
        elif model_type == "llama4_text":
            kwargs.setdefault("explicit_head_dim", 128)  # Llama4 class default
            kwargs.setdefault("rope_interleaved", True)
            if d.get("use_qk_norm", True):
                kwargs.setdefault("qk_l2_norm", True)
            kwargs.setdefault("attn_temperature_tuning", d.get("attn_temperature_tuning", True))
            kwargs.setdefault("attn_floor_scale", float(d.get("floor_scale", 8192)))
            kwargs.setdefault("attn_scale_coef", float(d.get("attn_scale", 0.1)))
            n = d.get("num_hidden_layers", 48)
            # Chunked local layers (3:1 with NoPE full layers by default).
            if "layer_sliding" not in kwargs:
                chunked = cls._sliding_pattern(
                    d, "llama4",
                    lambda i, nn: (i + 1) % 4 != 0,
                    token="chunked_attention",
                )
                kwargs.setdefault(
                    "attention_chunk_size", d.get("attention_chunk_size", 8192)
                )
                if not any(chunked):
                    kwargs["attention_chunk_size"] = None
                elif not all(chunked):
                    kwargs["layer_sliding"] = chunked
            # NoPE layers: no_rope_layers[i] == 0.
            nr = d.get("no_rope_layers") or [
                0 if (i + 1) % 4 == 0 else 1 for i in range(n)
            ]
            if len(nr) != n:
                raise ValueError(
                    f"llama4 no_rope_layers has {len(nr)} entries for {n} layers"
                )
            if not all(nr):
                kwargs.setdefault("layer_rope", tuple(bool(x) for x in nr))
            # MoE interleave: moe_layers when present, else every
            # interleave_moe_layer_step-th layer.
            step = d.get("interleave_moe_layer_step", 1)
            moe_layers = d.get("moe_layers")
            if moe_layers is None:
                moe_layers = [i for i in range(n) if (i + 1) % step == 0]
            if d.get("num_local_experts", 16) and moe_layers:
                kwargs.setdefault("num_local_experts", d.get("num_local_experts", 16))
                kwargs.setdefault("num_experts_per_tok", d.get("num_experts_per_tok", 1))
                if len(moe_layers) != n:
                    kwargs.setdefault(
                        "moe_layer_pattern",
                        tuple(i in set(moe_layers) for i in range(n)),
                    )
            else:
                kwargs["num_local_experts"] = 0
            kwargs.setdefault("intermediate_size_mlp", d.get("intermediate_size_mlp"))
        elif model_type == "llama4":
            return cls.from_hf_config(extract_text_config(d))
        elif model_type == "deepseek_v3":
            if not native:
                # Multi-head latent attention + DeepSeek MoE. Width convention
                # follows the llama4 branch so ONE rule serves both mixed
                # dense/MoE families: intermediate_size = the EXPERT width
                # (HF moe_intermediate_size), intermediate_size_mlp = the dense
                # layers' width (HF intermediate_size). Configs this framework
                # saved itself skip the derivation entirely — their native
                # field names round-tripped above, and re-deriving from HF
                # names would corrupt them (the width swap in particular).
                kwargs["kv_lora_rank"] = int(d.get("kv_lora_rank", 512))
                qlr = d.get("q_lora_rank")
                kwargs["q_lora_rank"] = int(qlr) if qlr else None
                kwargs["qk_nope_head_dim"] = int(d.get("qk_nope_head_dim", 128))
                kwargs["qk_rope_head_dim"] = int(d.get("qk_rope_head_dim", 64))
                kwargs["v_head_dim"] = int(d.get("v_head_dim", 128))
                # HF's head_dim here is the ROTARY dim (= qk_rope_head_dim),
                # not a projection width — the MLA head_dim property derives
                # qk_nope + qk_rope instead.
                kwargs["explicit_head_dim"] = None
                kwargs["rope_interleaved"] = bool(d.get("rope_interleave", True))
                if d.get("attention_bias"):
                    # HF: bias on q_a/q_proj, kv_a_proj_with_mqa, o_proj.
                    kwargs.setdefault("attention_in_bias", True)
                    kwargs.setdefault("attention_out_bias", True)
                n_routed = int(d.get("n_routed_experts") or 0)
                kwargs["num_local_experts"] = n_routed
                if n_routed:
                    kwargs["intermediate_size_mlp"] = int(
                        d.get("intermediate_size", 11008)
                    )
                    kwargs["intermediate_size"] = int(
                        d.get("moe_intermediate_size", 2048)
                    )
                    kwargs["num_experts_per_tok"] = int(
                        d.get("num_experts_per_tok", 8)
                    )
                    kwargs["moe_norm_topk_prob"] = bool(d.get("norm_topk_prob", True))
                    kwargs["moe_n_group"] = int(d.get("n_group", 1))
                    kwargs["moe_topk_group"] = int(d.get("topk_group", 1))
                    kwargs["moe_routed_scaling_factor"] = float(
                        d.get("routed_scaling_factor", 1.0)
                    )
                    nse = d.get("n_shared_experts")
                    # Preserve an explicit 0 (shared-expert-ablated
                    # checkpoint); only absent/None defaults to 1.
                    kwargs["n_shared_experts"] = (
                        1 if nse is None else int(nse)
                    )
                    first_dense = int(d.get("first_k_dense_replace", 0))
                    n = d.get("num_hidden_layers", 32)
                    pattern = tuple(i >= first_dense for i in range(n))
                    if not all(pattern):
                        kwargs["moe_layer_pattern"] = pattern
                # Attention scale: qk_head_dim^-0.5 x mscale(factor,
                # mscale_all_dim)^2 under yarn (DeepseekV3Attention.__init__);
                # expressed through query_pre_attn_scalar (scale = qps^-0.5).
                qk_hd = kwargs["qk_nope_head_dim"] + kwargs["qk_rope_head_dim"]
                rs_d = d.get("rope_scaling") or {}
                mad = rs_d.get("mscale_all_dim")
                if mad and float(rs_d.get("factor", 1.0)) > 1.0:
                    import math

                    m = 0.1 * float(mad) * math.log(float(rs_d["factor"])) + 1.0
                    kwargs["query_pre_attn_scalar"] = qk_hd / m**4
                else:
                    kwargs["query_pre_attn_scalar"] = float(qk_hd)
        elif model_type in ("mistral", "mixtral", "phi3"):
            # sliding_window flows through by field name (may be null);
            # mixtral's num_local_experts/num_experts_per_tok likewise.
            # phi3's fused qkv/gate_up projections are a CHECKPOINT layout
            # (split at conversion, utils/checkpoint.py), not a model delta;
            # its longrope scaling parses via the generic rope branch below.
            if model_type == "mixtral" and not d.get("num_local_experts"):
                raise ValueError("mixtral config without num_local_experts")
        else:
            raise NotImplementedError(
                f"model_type {model_type!r} is not supported "
                "(llama, mistral, phi3, qwen2, qwen3, qwen3_moe, mixtral, gemma, "
                "gemma2, gemma3_text, llama4_text, deepseek_v3 are)"
            )
        if model_type not in ("mixtral", "llama4_text", "qwen3_moe", "deepseek_v3"):
            # A stray num_local_experts key in a dense export must not flip
            # the model into MoE mode (same stray-key defence as
            # sliding_window above).
            kwargs["num_local_experts"] = 0
        if d.get("head_dim") and model_type != "deepseek_v3":
            # deepseek's top-level head_dim is the ROTARY dim, not a
            # projection width; the MLA head_dim property derives
            # qk_nope + qk_rope itself.
            kwargs["explicit_head_dim"] = d["head_dim"]
        kwargs.setdefault("num_key_value_heads", d.get("num_attention_heads", 32))
        for key in (
            "layer_sliding",
            "layer_rope",
            "moe_layer_pattern",
            "rope_long_factor",
            "rope_short_factor",
        ):
            if kwargs.get(key) is not None:
                # json round-trips tuples as lists; fields must stay hashable.
                kwargs[key] = tuple(kwargs[key])
        if kwargs.get("sliding_window") and kwargs.get("attention_chunk_size"):
            raise ValueError(
                "sliding_window and attention_chunk_size are mutually exclusive"
            )
        act = kwargs.get("hidden_act", "silu")
        if act not in SUPPORTED_ACTIVATIONS:
            # Must fail here, not as a KeyError deep inside a jitted forward.
            raise NotImplementedError(
                f"hidden_act {act!r} is not supported "
                f"(one of {sorted(SUPPORTED_ACTIVATIONS)})"
            )
        rs = d.get("rope_scaling") or {}
        if rs:
            kind = rs.get("rope_type", rs.get("type"))
            if kind not in ("linear", "llama3", "yarn", "longrope"):
                raise NotImplementedError(
                    f"rope_scaling type {kind!r} is not supported yet"
                )
            factor = float(rs.get("factor", 1.0))
            kwargs["rope_scaling_kind"] = kind
            kwargs["rope_scaling_factor"] = factor
            if kind == "llama3":
                kwargs["rope_low_freq_factor"] = float(rs.get("low_freq_factor", 1.0))
                kwargs["rope_high_freq_factor"] = float(rs.get("high_freq_factor", 4.0))
                kwargs["rope_original_max_position"] = int(
                    rs.get("original_max_position_embeddings", 8192)
                )
            elif kind == "yarn":
                import math

                kwargs["rope_beta_fast"] = float(rs.get("beta_fast") or 32)
                kwargs["rope_beta_slow"] = float(rs.get("beta_slow") or 1)
                kwargs["rope_truncate"] = bool(rs.get("truncate", True))
                kwargs["rope_original_max_position"] = int(
                    rs.get("original_max_position_embeddings")
                    or d.get("max_position_embeddings", 2048)
                )
                # HF _compute_yarn_parameters: attention_factor wins; else
                # derived from factor (and DeepSeek's mscale pair).
                af = rs.get("attention_factor")
                if af is None:
                    def get_mscale(scale, m=1.0):
                        return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

                    ms, mad = rs.get("mscale"), rs.get("mscale_all_dim")
                    af = (
                        get_mscale(factor, ms) / get_mscale(factor, mad)
                        if ms and mad
                        else get_mscale(factor)
                    )
                kwargs["rope_attention_factor"] = float(af)
            elif kind == "longrope":
                import math

                # transformers _compute_longrope_parameters: Phi-3 carries
                # original_max_position_embeddings at the config top level;
                # when present, the effective factor is the max/original
                # ratio (overriding any rope_scaling "factor" key). The
                # attention factor (applied to cos/sin in both regimes)
                # is sqrt(1 + ln(factor)/ln(original_max)) unless the
                # config names one explicitly.
                lf, sf = rs.get("long_factor"), rs.get("short_factor")
                if not lf or not sf:
                    raise ValueError(
                        "longrope rope_scaling needs long_factor and "
                        "short_factor lists"
                    )
                kwargs["rope_long_factor"] = tuple(float(x) for x in lf)
                kwargs["rope_short_factor"] = tuple(float(x) for x in sf)
                max_pos = int(d.get("max_position_embeddings", 2048))
                orig = d.get("original_max_position_embeddings") or rs.get(
                    "original_max_position_embeddings"
                )
                if orig:
                    factor = max_pos / int(orig)
                else:
                    orig = max_pos
                kwargs["rope_original_max_position"] = int(orig)
                af = rs.get("attention_factor")
                if af is None:
                    af = (
                        1.0
                        if factor <= 1.0
                        else math.sqrt(1 + math.log(factor) / math.log(int(orig)))
                    )
                kwargs["rope_attention_factor"] = float(af)
                kwargs["rope_scaling_factor"] = float(factor)
        cfg = cls(**kwargs)
        if cfg.rope_scaling_kind == "longrope":
            for nm, fac in (
                ("long_factor", cfg.rope_long_factor),
                ("short_factor", cfg.rope_short_factor),
            ):
                if fac is None or len(fac) != cfg.head_dim // 2:
                    raise ValueError(
                        f"longrope {nm} needs {cfg.head_dim // 2} entries "
                        f"(head_dim {cfg.head_dim}), got "
                        f"{None if fac is None else len(fac)}"
                    )
        return cfg

    @classmethod
    def from_pretrained(cls, model_path: str) -> "LlamaConfig":
        with open(os.path.join(model_path, "config.json")) as f:
            return cls.from_hf_config(json.load(f))


@dataclasses.dataclass(frozen=True)
class FrameworkConfig:
    """Runtime flags — the same surface as the reference CLI
    (``/root/reference/main.py:30-49``) plus TPU-specific knobs.

    ``storage_location`` gains a ``tpu`` value (activations stay in HBM); the
    reference's ``gpu`` is accepted as an alias. Unlike the reference's
    ``--data_parallel`` bool footgun (any non-empty string parsed as True,
    ``/root/reference/main.py:40``), this is a real bool everywhere.
    """

    model_path: str = "./"
    num_batch: int = 1
    layer_num_per_shard: int = 1
    storage_location: str = "cpu"  # 'tpu' | 'cpu' | 'disk' ('gpu' alias of 'tpu')
    max_activation_in_cpu: int = 100
    data_parallel: bool = False
    disk_folder: str = "./temp"
    num_gen_token: int = 1
    # --- TPU-specific knobs (not in the reference) ---
    max_token_len: int = DEFAULT_MAX_TOKEN_LEN
    dtype: str = "bfloat16"  # compute/storage dtype on device ('float16'|'bfloat16'|'float32')
    block_size: int = 8  # prompts batched together per jitted layer call
    # Shards prefetched ahead of compute (0 = synchronous, the reference's
    # serialized schedule). None = auto: 2 on an accelerator backend (overlap
    # the host->HBM upload of shard t+1 with shard t's compute), 0 on the CPU
    # backend — there "device" memory IS host memory, so there is no transfer
    # link to overlap and the producer thread only steals cores/GIL from
    # XLA:CPU's own compute (measured: prefetch=2 is ~10% SLOWER than the
    # serialized schedule on CPU; see bench.py).
    prefetch_depth: int | None = None
    num_devices: int = 0  # 0 = all visible devices
    bucket_multiple: int = 64  # sequence lengths padded up to a multiple of this
    # Pallas flash-attention kernels. None = auto: enabled on TPU, where they
    # measure 2-3.5x faster than the XLA attention at 4k context (bench.py's
    # pallas_speedup_4k); shapes the kernel can't tile fall back per-call
    # (models/llama.py checks pallas_attention.supports() at trace time).
    use_pallas: bool | None = None
    # Tensor parallelism for the streaming scorer: shard every streamed
    # layer's matmuls Megatron-style over this many chips (per-chip weight
    # HBM drops by the factor; XLA emits the ICI all-reduces). 1 = off.
    # Composes with data_parallel (dp groups of tp chips); supersedes the MP
    # pipeline when set.
    tensor_parallel: int = 1
    verbose_metrics: bool = False  # one JSON line per structured event (stderr)
    profile_dir: str = ""  # jax.profiler trace output dir ("" = off)
    # Sweep-timeline span tracing (obs/trace.py): record shard loads,
    # device puts, compute, source waits, cache hits, pin loads, retry/
    # heal events, and (serving) the wave lifecycle into a bounded ring,
    # correlated by sweep_id/shard_idx/wave_id/request_id. Zero-cost
    # no-op when False. The CLIs export at run end to ``trace_out``
    # (Chrome trace-event JSON — Perfetto-loadable — or JSONL when the
    # path ends in .jsonl); ``cli trace-report`` analyzes the file.
    trace: bool = False
    trace_out: str = ""  # "" = default fls_trace.json when trace is on
    # Black-box flight recorder (obs/events.py + obs/incident.py;
    # docs/incidents.md). journal_dir enables the durable append-only
    # JSONL event journal every failure-path site writes through
    # (engine recoveries, wave aborts, replica death/drain/redispatch,
    # quarantines, re-read heals, pressure steps, watchdog stalls,
    # preemptions, SLO budget exhaustion). "" = off (zero cost: one
    # bool check per failure event). The journal rotates atomically at
    # journal_max_mb (one previous generation kept) and a write failure
    # degrades to a counted drop, never an engine error.
    journal_dir: str = ""
    journal_max_mb: float = 16.0
    # incidents_dir arms the incident recorder: a journal event at (or
    # above) incident_trigger severity captures a self-contained bundle
    # directory — journal tail, full metrics snapshot, trace ring as
    # Chrome trace JSON, resolved config, manifest — debounced so a
    # failure storm yields ONE bundle (the capture settles
    # incident_settle_s after the trigger, extended while trigger-level
    # events keep landing, then debounces for incident_debounce_s).
    # The dir is disk-budgeted at incidents_max_mb, oldest evicted.
    # Setting incidents_dir without journal_dir keeps the journal
    # beside the bundles. "" = off.
    incidents_dir: str = ""
    incidents_max_mb: float = 256.0
    incident_trigger: str = "error"  # info|warning|error|critical
    incident_debounce_s: float = 60.0
    incident_settle_s: float = 1.0
    resume: bool = False  # disk mode: resume from the last completed shard
    # Long context: prompts whose PREFIX exceeds max_token_len are scored
    # exactly via sequence parallelism (ring attention over an 'sp' mesh of
    # the visible chips; cap becomes n_chips * max_token_len) instead of the
    # reference's silent truncation (/root/reference/utils.py:14,250,254).
    long_context: bool = False
    # Weights-resident KV decode: when the model's device-materialised
    # weights fit comfortably in HBM, keep every streamed shard on chip
    # after the prefill pass and run decode steps with ZERO weight
    # transfers (the reference re-streams the full model per token,
    # /root/reference/main.py:65-76; plain KV decode still re-streams the
    # weights each step). 'auto' = on iff total weight bytes (for the
    # compute dtype, split over the tp/mp chips) fit within 45% of the
    # chip's known HBM — leaving room for KV caches, activations, and the
    # prefill-time prefetch queue; unknown HBM resolves to off.
    decode_resident: str = "auto"  # 'auto' | 'on' | 'off'
    # Fused decode: run ALL greedy decode steps as one jitted scan per block
    # (runtime/decode._fused_decode_steps) instead of one dispatch per shard
    # per step. 'auto' fuses whenever the preconditions hold (weights
    # resident, greedy selection, one placement target); 'on' additionally
    # raises if they don't (so a user asking for it learns why not); 'off'
    # keeps the per-step loop (bitwise-stable vs the streamed path — fusing
    # changes XLA fusion boundaries, so float results can differ in the
    # last ulp).
    decode_fused: str = "auto"  # 'auto' | 'on' | 'off'
    # Speculative decode (kv_cache mode): each streamed pass verifies
    # `speculative_k` prompt-lookup-drafted tokens PLUS the next token in
    # one K+1-position decode step, emitting 1..K+1 tokens per pass —
    # dividing the number of full weight streams per generated token by the
    # acceptance factor. Greedy-exact (verification accepts precisely the
    # tokens sequential greedy would emit); 0 disables. Ignored when the
    # fused resident path engages (resident steps don't re-stream weights,
    # so there is nothing to amortise).
    speculative_k: int = 0
    # Sampling controls (generation_loop.sample_token semantics): 0 = greedy
    # argmax (exact reference behaviour, /root/reference/main.py:47-48 left
    # the temperature flag commented out). Deterministic given seed.
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    # Transient-I/O retry knobs (faults/retry.py RetryPolicy; the weight
    # stream's disk reads and host->device puts retry under this before a
    # typed ShardLoadError surfaces). attempts=1 disables retrying.
    io_retry_attempts: int = 4
    io_retry_base_s: float = 0.05  # first backoff; doubles per attempt
    io_retry_deadline_s: float = 60.0  # overall wall cap per call; 0 = none
    # Weight-stream integrity verification (integrity/manifest.py): every
    # layer load checksums its tensors against the model dir's
    # integrity.json; a mismatch retries (re-read heals page-cache/NFS
    # corruption) and only persistent corruption raises a typed
    # ShardCorruptError. The crc pass is amortized: a file generation is
    # hashed once and later sweeps reuse the cached clean verdict (any
    # on-disk change re-verifies), so steady-state sweeps pay no per-byte
    # hash cost. Dirs with no manifest load unverified with a one-time
    # warning.
    verify_weights: bool = True
    # Host-resident shard cache (runtime/hostcache.py): pins fully-built,
    # upload-ready host shard trees so steady-state sweeps (the serving
    # engine's cycling source, multi-sweep offline decode) skip disk read
    # + parse + checksum entirely and go straight to device_put. None =
    # auto: a fraction of the host's available RAM, and OFF while fault
    # injection is enabled (chaos runs must exercise the per-load fault
    # sites every sweep). 0 disables; any other value is a budget in GB.
    # Entries are stat-guarded and invalidated on quarantine/manifest
    # change, so PR 4's corruption self-healing is unaffected.
    host_cache_gb: float | None = None
    # Paged prefix-KV pool (runtime/kvpool.py): process-lived, refcounted
    # pages share a recurring prefix's post-RoPE KV across admission waves
    # with copy-on-write at the first divergent token, so a hot system
    # prompt prefills once per PROCESS instead of once per wave.
    # kv_page_tokens: rows per page (the sharing granularity; <= 0
    # disables the pool). kv_pool_gb: host-RAM budget for resident pages —
    # None = auto (a small slice of available RAM; unlike the shard cache
    # it stays ON under fault injection, because the pool's spill reads
    # are themselves chaos sites), 0 disables. kv_host_spill: True spills
    # cold pages to checksummed disk files that heal on read (PR 4
    # machinery); False drops them (the prefix simply re-prefills later).
    kv_page_tokens: int = 16
    kv_pool_gb: float | None = None
    kv_host_spill: bool = True
    # Device residency tier (runtime/residency.py): HBM byte budget for
    # pinning the hottest layers (embedding, lm_head, final norm, then as
    # many transformer blocks as fit) permanently on chip — pinned layers
    # are subtracted from every sweep's weight stream, cutting the
    # host->HBM link traffic by exactly their bytes while outputs stay
    # token-identical. None = auto: measured free HBM minus an activation
    # headroom (ACTIVATION_HEADROOM_FRACTION), OFF under fault injection
    # (chaos schedules must keep their per-load draws; an explicit budget
    # still wins) and on chips with unknown HBM. 0 (default) disables.
    # Pins are loaded once through the manifest-verified path and survive
    # serving source restarts and wave recoveries; a pin-time load whose
    # corruption survives every re-read is demoted back to streaming, so
    # wrong bytes are never resident.
    hbm_pin_gb: float | None = 0.0
    # Threads in the loader's page-cache readahead pool
    # (utils/native.py FilePrefetcher — posix_fadvise(WILLNEED) issuers,
    # ~zero CPU each; more threads help deep dirs on high-QD storage).
    readahead_threads: int = 2
    # Device-resident score cap (executor.ScoreSink): at most this many
    # head-stage score slices stay pending on device before older ones
    # resolve to host numpy. Larger values defer host syncs further on
    # big-batch runs at the cost of HBM for the pending slices.
    score_sink_max_device: int = 16
    # Deterministic fault injection (off by default; the --chaos CLI flag
    # and the chaos tests enable it). Frozen sub-config keeps this config
    # hashable.
    faults: FaultConfig = dataclasses.field(default_factory=FaultConfig)
    # Resource-pressure brownout ladder (off by default; the --pressure
    # CLI flag enables it — runtime/pressure.py, docs/pressure.md).
    pressure: PressureConfig = dataclasses.field(default_factory=PressureConfig)
    # Multi-tenant LoRA adapter serving (off by default; --adapter_dir
    # enables it — adapters/, docs/adapters.md).
    adapters: AdapterConfig = dataclasses.field(default_factory=AdapterConfig)

    def __post_init__(self) -> None:
        loc = self.storage_location
        if loc == "gpu":
            object.__setattr__(self, "storage_location", "tpu")
        elif loc not in ("tpu", "cpu", "disk"):
            raise ValueError(f"storage_location must be tpu|cpu|disk, got {loc!r}")
        if self.layer_num_per_shard < 1:
            raise ValueError("layer_num_per_shard must be >= 1")
        if self.num_batch < 1:
            raise ValueError("num_batch must be >= 1")
        if self.num_gen_token < 1:
            # 0 would deadlock DP decode: the broadcast source is built with
            # rounds=num_gen_token (1 in resident mode), so its producer
            # would push nothing while every consumer blocks on an empty
            # queue.
            raise ValueError("num_gen_token must be >= 1")
        if self.tensor_parallel < 1:
            raise ValueError("tensor_parallel must be >= 1")
        if self.prefetch_depth is not None and self.prefetch_depth < 0:
            raise ValueError("prefetch_depth must be >= 0 (or None for auto)")
        # tensor_parallel + data_parallel COMPOSE: the visible chips
        # partition into dp groups of tp chips each; every group streams the
        # model Megatron-sharded over its own tp sub-mesh while the prompt
        # batch splits across groups (orchestration validates the chip
        # count at run time, when the device list is known).
        if (self.top_k or self.top_p) and self.temperature <= 0:
            # Silent no-op filters would masquerade as sampling.
            raise ValueError("top_k/top_p require temperature > 0")
        if self.decode_resident not in ("auto", "on", "off"):
            raise ValueError(
                "decode_resident must be auto|on|off, "
                f"got {self.decode_resident!r}"
            )
        if self.decode_fused not in ("auto", "on", "off"):
            raise ValueError(
                f"decode_fused must be auto|on|off, got {self.decode_fused!r}"
            )
        if not 0 <= self.speculative_k <= 64:
            raise ValueError(
                f"speculative_k must be in [0, 64], got {self.speculative_k}"
            )
        if self.speculative_k and self.temperature > 0:
            # Greedy verification is exact; sampled verification would need
            # rejection sampling to preserve the output distribution —
            # loudly unsupported rather than silently wrong.
            raise ValueError("speculative_k requires greedy (temperature=0)")
        if self.io_retry_attempts < 1:
            raise ValueError("io_retry_attempts must be >= 1")
        if self.io_retry_base_s < 0 or self.io_retry_deadline_s < 0:
            raise ValueError("io_retry_base_s/io_retry_deadline_s must be >= 0")
        if self.host_cache_gb is not None and self.host_cache_gb < 0:
            raise ValueError(
                "host_cache_gb must be >= 0 (or None for auto), got "
                f"{self.host_cache_gb}"
            )
        if self.kv_pool_gb is not None and self.kv_pool_gb < 0:
            raise ValueError(
                "kv_pool_gb must be >= 0 (or None for auto), got "
                f"{self.kv_pool_gb}"
            )
        if self.hbm_pin_gb is not None and self.hbm_pin_gb < 0:
            raise ValueError(
                "hbm_pin_gb must be >= 0 (or None for auto), got "
                f"{self.hbm_pin_gb}"
            )
        if self.readahead_threads < 1:
            raise ValueError("readahead_threads must be >= 1")
        if self.score_sink_max_device < 1:
            raise ValueError("score_sink_max_device must be >= 1")
        if self.journal_max_mb <= 0:
            raise ValueError("journal_max_mb must be > 0")
        if self.incidents_max_mb <= 0:
            raise ValueError("incidents_max_mb must be > 0")
        if self.incident_trigger not in ("info", "warning", "error", "critical"):
            raise ValueError(
                "incident_trigger must be info|warning|error|critical, "
                f"got {self.incident_trigger!r}"
            )
        if self.incident_debounce_s < 0 or self.incident_settle_s < 0:
            raise ValueError(
                "incident_debounce_s/incident_settle_s must be >= 0"
            )

    def effective_host_cache_bytes(self) -> int:
        """Resolve the tri-state ``host_cache_gb`` to a byte budget.

        Explicit value -> that many GB (0 = off). None (auto) -> a
        fraction of the host's currently-available RAM — except under
        fault injection, where auto resolves to OFF: the chaos sites fire
        inside the per-load read path, and a cache hit would silently
        skip the very draws a seeded chaos schedule exists to make (an
        EXPLICIT budget still wins for chaos cache-parity tests). Unknown
        free RAM (non-Linux) also resolves to off."""
        if self.host_cache_gb is not None:
            return int(self.host_cache_gb * 1e9)
        if self.faults.enabled:
            return 0
        from flexible_llm_sharding_tpu.runtime.hostcache import (
            auto_budget_bytes,
        )

        return auto_budget_bytes()

    def effective_kv_pool_bytes(self) -> int:
        """Resolve the tri-state ``kv_pool_gb`` to a byte budget.

        Explicit value -> that many GB (0 = off). None (auto) -> a small
        slice of the host's available RAM (kvpool._auto_budget_bytes).
        Unlike the shard cache, auto stays ON under fault injection: the
        pool's spill reads are themselves corrupt_activation chaos sites,
        so chaos runs keep (and exercise) their draws through the pool."""
        if self.kv_pool_gb is not None:
            return int(self.kv_pool_gb * 1e9)
        from flexible_llm_sharding_tpu.runtime.kvpool import (
            _auto_budget_bytes,
        )

        return _auto_budget_bytes()

    def effective_adapter_bytes(self) -> int:
        """Resolve the tri-state ``adapters.max_gb`` to a byte budget.

        Explicit value -> that many GB (0 = off). None (auto) -> a small
        slice of the host's available RAM (adapters.loader's auto
        budget). Like the KV pool — and unlike the shard cache — auto
        stays ON under fault injection: the adapter store's delta reads
        are themselves ``corrupt_shard`` chaos sites (the chaos smoke
        serves adapters *under* faults), so chaos runs must keep their
        draws rather than lose the store entirely."""
        if self.adapters.max_gb is not None:
            return int(self.adapters.max_gb * 1e9)
        from flexible_llm_sharding_tpu.adapters.loader import (
            _auto_budget_bytes,
        )

        return _auto_budget_bytes()

    def effective_hbm_pin_bytes(self, device=None) -> int:
        """Resolve the tri-state ``hbm_pin_gb`` to a pin-tier byte budget.

        Explicit value -> that many GB (0 = off). None (auto) -> measured
        free HBM minus the activation headroom
        (residency.auto_pin_budget_bytes) — except under fault injection,
        where auto resolves to OFF: pinned layers skip the per-sweep load
        path, silently starving a seeded chaos schedule of its draws (an
        EXPLICIT budget still wins, for chaos pin-parity tests). Unknown
        HBM (the CPU backend, unrecognized chips) also resolves to off."""
        if self.hbm_pin_gb is not None:
            return int(self.hbm_pin_gb * 1e9)
        if self.faults.enabled:
            return 0
        from flexible_llm_sharding_tpu.runtime.residency import (
            auto_pin_budget_bytes,
        )

        return auto_pin_budget_bytes(device)

    def retry_policy(self):
        """The transient-I/O RetryPolicy for this run's weight stream
        (imported lazily: faults/inject.py imports this module)."""
        from flexible_llm_sharding_tpu.faults.retry import RetryPolicy

        return RetryPolicy(
            max_attempts=self.io_retry_attempts,
            base_delay_s=self.io_retry_base_s,
            deadline_s=self.io_retry_deadline_s or None,
        )

    def effective_prefetch_depth(self) -> int:
        """Resolve the tri-state ``prefetch_depth``: explicit value, or auto —
        2 when the default backend is an accelerator (real host->HBM link to
        hide), 0 on CPU (the overlapped schedule degenerates: no link, and
        the producer thread contends with XLA:CPU compute for cores)."""
        if self.prefetch_depth is not None:
            return self.prefetch_depth
        try:
            import jax

            return 2 if jax.devices()[0].platform != "cpu" else 0
        except Exception:
            return 0

    def decode_resident_enabled(
        self, model_cfg, n_weight_chips: int = 1, device=None
    ) -> bool:
        """Resolve the tri-state ``decode_resident`` for a model.

        ``n_weight_chips``: how many chips the streamed weights divide over
        (tensor_parallel width, or the MP pipeline's stage count) — residency
        is judged per chip. Auto requires a KNOWN HBM capacity; the CPU
        backend (tests) and unrecognised devices resolve to off, so the
        fast path is only ever taken where the budget is real.
        """
        if self.decode_resident == "on":
            return True
        if self.decode_resident == "off":
            return False
        from flexible_llm_sharding_tpu.utils.metrics import (
            chip_hbm_gb,
            weight_bytes_per_chip,
        )

        try:
            hbm_gb = chip_hbm_gb(device)
        except Exception:
            return False
        if not hbm_gb:
            return False
        per_chip = weight_bytes_per_chip(model_cfg, self.dtype, n_weight_chips)
        return per_chip <= 0.45 * hbm_gb * 1e9

    def pallas_enabled(self) -> bool:
        """Resolve the tri-state ``use_pallas``: explicit value, or auto —
        on iff the default backend's devices are real TPUs (the kernels are
        2-3.5x faster there; in interpret mode they'd only be slower)."""
        if self.use_pallas is not None:
            return self.use_pallas
        try:
            import jax

            return jax.devices()[0].platform == "tpu"
        except Exception:
            return False


def _parse_tenant_map(spec: str, what: str) -> dict[str, float]:
    """Parse a ``"tenantA=2,tenantB=0.5"`` CLI spec into ``{tenant: value}``.
    Shared by SchedConfig's weight and rate-limit fields so the two can't
    grow divergent syntaxes; raises ValueError naming the offending entry."""
    out: dict[str, float] = {}
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        name, sep, value = entry.partition("=")
        if not sep or not name:
            raise ValueError(
                f"{what}: bad entry {entry!r} (expected tenant=value)"
            )
        try:
            out[name] = float(value)
        except ValueError:
            raise ValueError(
                f"{what}: non-numeric value in {entry!r}"
            ) from None
    return out


@dataclasses.dataclass(frozen=True)
class SchedConfig:
    """Multi-tenant sweep scheduler (serve/sched/; docs/scheduling.md).

    Off by default — the admission queue then pops strict FIFO, exactly
    the pre-scheduler serving path. Enabled (``--sched``), the queue pops
    by STRICT PRIORITY across SLO classes (interactive > standard >
    best_effort) with deficit-weighted round-robin across tenants inside
    a class, tenants can carry token-bucket rate limits (over-limit
    submits resolve as typed ``RateLimited`` rejections with a
    ``retry_after_s`` hint), an interactive request stuck behind
    best-effort waves preempts the youngest best-effort wave at a
    shard-0 sweep boundary (never mid-sweep; the preempted requests
    resume token-identically), and same-prefix requests coalesce into
    one shared-prefix prefill."""

    enabled: bool = False
    # Per-class default ADMISSION deadlines (seconds), applied when a
    # request names neither its own deadline nor one via the serve-level
    # default; 0 = no class default (fall back to
    # ServeConfig.default_deadline_s).
    interactive_deadline_s: float = 0.0
    standard_deadline_s: float = 0.0
    best_effort_deadline_s: float = 0.0
    # Deficit-round-robin weights: "tenantA=4,tenantB=1"; unlisted
    # tenants weigh 1. A tenant with weight w gets ~w shares of each
    # class's admission budget while it has queued work.
    tenant_weights: str = ""
    # Token-bucket rate limits in requests/second: "tenantA=5"; unlisted
    # tenants are unlimited. Over-limit submits resolve as typed
    # RateLimited (a QueueFull subclass) carrying retry_after_s.
    tenant_limits: str = ""
    # Bucket capacity (burst) in requests, shared by every limited
    # tenant: a tenant idle long enough accumulates up to this many
    # instantly-admittable requests.
    tenant_burst: float = 4.0
    # Sweep-boundary preemption: an interactive request waiting while
    # every active-request slot is held and a best-effort wave is in
    # flight retires the YOUNGEST best-effort wave at the next shard-0
    # boundary; its requests re-enqueue with generated-so-far tokens
    # folded into their suffixes and resume token-identically.
    preempt: bool = True
    # Admission-time prefix coalescing: same-tokenized-prefix requests
    # admitted at one boundary merge into one wave entry that prefills
    # the shared prefix KV once and fans the suffix/decode streams out
    # per request.
    coalesce: bool = True
    # Fleet routing (serve/router.py): multiply the router's phase
    # weight by this for interactive requests, so interactive work lands
    # on the replica nearest its next shard-0 admission point.
    interactive_phase_boost: float = 2.0

    def __post_init__(self) -> None:
        for name in ("interactive_deadline_s", "standard_deadline_s",
                     "best_effort_deadline_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0 (0 = no default)")
        weights = _parse_tenant_map(self.tenant_weights, "tenant_weights")
        for t, w in weights.items():
            # The DRR loop's visit bound is ~1/min_weight; a zero or
            # absurdly small weight would spin it, not starve gracefully.
            if not 0.01 <= w <= 1e6:
                raise ValueError(
                    f"tenant_weights: weight for {t!r} must be in "
                    f"[0.01, 1e6], got {w}"
                )
        limits = _parse_tenant_map(self.tenant_limits, "tenant_limits")
        for t, r in limits.items():
            if r <= 0:
                raise ValueError(
                    f"tenant_limits: rate for {t!r} must be > 0 "
                    "(omit the tenant for unlimited)"
                )
        if self.tenant_burst < 1:
            raise ValueError("tenant_burst must be >= 1")
        if self.interactive_phase_boost < 1:
            raise ValueError(
                "interactive_phase_boost must be >= 1 (1 = no boost)"
            )

    def tenant_weight_map(self) -> dict[str, float]:
        return _parse_tenant_map(self.tenant_weights, "tenant_weights")

    def tenant_limit_map(self) -> dict[str, float]:
        return _parse_tenant_map(self.tenant_limits, "tenant_limits")


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """SLO targets + error budgets (obs/slo.py; docs/incidents.md has
    the budget math). Off by default — the per-class latency exports
    then carry no contract, exactly the pre-SLO behaviour.

    Enabled, the tracker turns the existing ``ttft_by_class`` /
    ``latency_by_class`` streams into error-budget accounting: a p95
    target allows 5% of samples over the line, the burn rate is the
    violating fraction over that allowance, and a class that exhausts
    its budget (burn rate >= 1 with at least ``min_samples`` samples)
    emits an ``slo_budget_exhausted`` journal event — which, with the
    incident recorder armed, captures a bundle exactly like a crash."""

    enabled: bool = False
    # Per-class p95 TTFT targets in seconds, the tenant-map syntax:
    # "interactive=0.5,standard=2.0" (unlisted classes carry no target).
    ttft_p95_s: str = ""
    # Aggregate per-token decode-latency p95 target in seconds (0 = off).
    token_latency_p95_s: float = 0.0
    # Availability target as a fraction of requests that must complete
    # (e.g. 0.999); failed requests burn the 1-target budget. 0 = off.
    availability_target: float = 0.0
    # Budgets are not judged (no exhaustion events) below this many
    # samples — a single slow first request must not trip a page.
    min_samples: int = 20

    def __post_init__(self) -> None:
        targets = _parse_tenant_map(self.ttft_p95_s, "ttft_p95_s")
        if targets:
            # Lazy import: utils.metrics mirrors the sched class names
            # (importing serve here would cycle); config stays light.
            from flexible_llm_sharding_tpu.utils.metrics import (
                SLO_CLASS_NAMES,
            )
        for cls, target in targets.items():
            if cls not in SLO_CLASS_NAMES:
                raise ValueError(
                    f"ttft_p95_s: unknown SLO class {cls!r} "
                    f"(one of {SLO_CLASS_NAMES})"
                )
            if target <= 0:
                raise ValueError(
                    f"ttft_p95_s: target for {cls!r} must be > 0 "
                    "(omit the class for no target)"
                )
        if self.token_latency_p95_s < 0:
            raise ValueError("token_latency_p95_s must be >= 0 (0 = off)")
        if not 0.0 <= self.availability_target < 1.0:
            raise ValueError(
                "availability_target must be in [0, 1) — 0 disables, "
                "1.0 would allow no failures ever (an unpayable budget)"
            )
        if self.min_samples < 1:
            raise ValueError("min_samples must be >= 1")

    def ttft_target_map(self) -> dict[str, float]:
        return _parse_tenant_map(self.ttft_p95_s, "ttft_p95_s")


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Closed-loop fleet elasticity (serve/autoscale.py; docs/autoscale.md
    has the interlock table and stagger math). Off by default — the fleet
    then stays at the static ``replicas`` count, exactly the pre-autoscale
    behaviour.

    Enabled, a ``FleetAutoscaler`` control loop polls the signals the repo
    already trusts under chaos — SLO burn rate (obs/slo.py), queue depth
    watermarks, and the brownout pressure level (runtime/pressure.py) —
    and drives ``add_replica``/``remove_replica(drain=True)`` between
    ``min``/``max``, with anti-flap machinery (consecutive-poll
    confirmation, separate grow/shrink cooldowns) and hard interlocks
    (never grow at shed-or-above pressure, never shrink below min or over
    an in-flight drain, WAL replay completes before the first decision).
    The same config carries the sweep-phase stagger controller: replicas
    hold at their shard-0 boundary (bounded) until their sweep offsets sit
    at i/N, so worst-case admission wait drops to sweep/N."""

    enabled: bool = False
    # Fleet size bounds the controller may move between. The static
    # ``--replicas`` count is the starting population and must sit inside
    # [min, max] (cross-validated by ServeConfig).
    min: int = 1
    max: int = 4
    # Controller poll interval (seconds) — decisions are made at most
    # once per poll, and confirmation counts in polls.
    poll_s: float = 1.0
    # Grow when the worst per-class SLO burn rate sustains at or above
    # this (burn 1.0 = spending the whole error budget) OR the queue
    # depth fraction sustains at or above grow_queue_frac.
    grow_burn_rate: float = 1.0
    grow_queue_frac: float = 0.75
    # Shrink only when burn AND queue are BOTH below these (hysteresis:
    # the shrink thresholds sit well under the grow ones, so a reading
    # between the bands holds steady instead of oscillating).
    shrink_burn_rate: float = 0.25
    shrink_queue_frac: float = 0.10
    # A breach must persist this many CONSECUTIVE polls before acting —
    # a single spiky sample never scales the fleet.
    confirm_polls: int = 3
    # Per-direction cooldowns (seconds) after ANY scale action: grow
    # again only after grow_cooldown_s, shrink only after
    # shrink_cooldown_s (shrink waits longer by default — capacity is
    # cheap to hold and expensive to miss).
    grow_cooldown_s: float = 10.0
    shrink_cooldown_s: float = 30.0
    # Journal every decision without acting (autoscale_* events carry
    # dry_run=True) — the shadow-mode rehearsal before trusting the loop.
    dry_run: bool = False
    # --- sweep-phase stagger (ROADMAP item 4: sweep/N admission wait) ---
    # Control replica sweep offsets to i/N via bounded boundary holds.
    stagger: bool = True
    # Normalized stagger error (0 = perfect i/N spread, 1 = all replicas
    # in phase) at or under this counts as converged; the controller only
    # injects holds while above it.
    stagger_tolerance: float = 0.15
    # Per-boundary hold cap as a fraction of one measured sweep wall —
    # a hold can never stall a replica longer than this per sweep.
    stagger_hold_max_frac: float = 0.5

    def __post_init__(self) -> None:
        if self.min < 1:
            raise ValueError("autoscale min must be >= 1")
        if self.max < self.min:
            raise ValueError("autoscale max must be >= min")
        if self.poll_s <= 0:
            raise ValueError("autoscale poll_s must be > 0")
        if self.grow_burn_rate < 0 or self.shrink_burn_rate < 0:
            raise ValueError("autoscale burn-rate thresholds must be >= 0")
        if self.shrink_burn_rate > self.grow_burn_rate:
            raise ValueError(
                "autoscale shrink_burn_rate must be <= grow_burn_rate "
                "(the hysteresis band would invert)"
            )
        for name in ("grow_queue_frac", "shrink_queue_frac"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"autoscale {name} must be in [0, 1]")
        if self.shrink_queue_frac > self.grow_queue_frac:
            raise ValueError(
                "autoscale shrink_queue_frac must be <= grow_queue_frac "
                "(the hysteresis band would invert)"
            )
        if self.confirm_polls < 1:
            raise ValueError("autoscale confirm_polls must be >= 1")
        if self.grow_cooldown_s < 0 or self.shrink_cooldown_s < 0:
            raise ValueError("autoscale cooldowns must be >= 0")
        if not 0.0 < self.stagger_tolerance <= 1.0:
            raise ValueError(
                "autoscale stagger_tolerance must be in (0, 1]"
            )
        if not 0.0 <= self.stagger_hold_max_frac <= 1.0:
            raise ValueError(
                "autoscale stagger_hold_max_frac must be in [0, 1]"
            )


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Online-serving knobs (the ``serve`` CLI subcommand / serve.engine).

    The offline flags (FrameworkConfig) describe ONE batch run; these
    describe the server wrapped around the same runtime: how many requests
    may wait (admission queue), how many join per wave at a shard-0
    boundary, and how long a request may sit queued before it is evicted.
    """

    # Admission queue capacity: submissions beyond this are rejected
    # immediately with a reason (backpressure) instead of queueing unbounded.
    queue_capacity: int = 64
    # Most requests coalesced into ONE wave at a shard-0 boundary — the
    # prefill batch size. A wave's blocks ride every subsequent sweep, so
    # the knob bounds per-wave prefill latency AND per-sweep KV footprint.
    max_wave_requests: int = 8
    # Total in-flight requests across all active waves; the batcher stops
    # admitting (requests keep queueing) until completions free slots.
    max_active_requests: int = 32
    # Per-request generation budget when the request doesn't name one.
    default_max_new_tokens: int = 16
    # Queue-wait deadline (seconds) applied to requests that don't carry
    # their own: a request not ADMITTED within this window is evicted with
    # status 'expired' (0 = no deadline). Time-to-first-token is the online
    # contract; serving a long-expired request wastes sweeps the live ones
    # need.
    default_deadline_s: float = 0.0
    # Engine idle poll (seconds) while no wave is active and the queue is
    # empty. Admission itself is boundary-driven, not polled: with waves in
    # flight the queue is re-checked at every shard-0 boundary.
    idle_poll_s: float = 0.01
    # Periodic structured stats line (JSON to stderr) every this many
    # seconds; 0 = off. Snapshot of queue depth, active requests, TTFT and
    # per-token latency summaries, admitted/rejected/expired counters.
    stats_interval_s: float = 0.0
    # Step-progress watchdog (streamed-weights mode): if a sweep makes no
    # shard progress for this many seconds, the engine aborts the weight
    # source, fails ONLY the in-flight waves (their futures resolve with a
    # structured WaveAborted instead of hanging forever), restarts the
    # source, and keeps serving. 0 = off.
    watchdog_abort_s: float = 0.0
    # Prometheus metrics endpoint (obs/registry.py MetricsServer): serve
    # /metrics (text exposition) and /metrics.json on 127.0.0.1 at this
    # port — queue depth, TTFT quantiles, streamed bytes, cache hit rate,
    # residency savings, retry/heal/recovery counters in one scrape.
    # None = off; 0 = bind an ephemeral port (tests/parallel engines; the
    # bound port is engine.metrics_server.port).
    metrics_port: int | None = None
    # --- replica fleet (serve/fleet.py; engaged by the CLI when > 1) ---
    # N ServeEngine replicas behind a shard-phase-aware router: each runs
    # its own sweep thread, all share the process host shard cache (a
    # recycled replica re-warms instantly). Requests dispatch to the
    # healthiest replica; a dead replica's queued and in-flight requests
    # re-dispatch to a survivor exactly once, token-identically.
    replicas: int = 1
    # Router score = phase_weight * boundary_frac + depth_weight * load
    # (serve/router.py): boundary_frac is the fraction of a sweep left
    # until the replica's next shard-0 admission point, load its
    # (queued + active) / max_active_requests. Lowest score wins.
    router_phase_weight: float = 1.0
    router_depth_weight: float = 1.0
    # Fleet health-monitor poll interval (seconds): each tick reads every
    # replica's registry health (engine_recoveries, watchdog stalls) and
    # sweep-progress watermark; a busy replica whose watermark stalls past
    # watchdog_abort_s is declared dead and hard-failed (watchdog_abort_s
    # 0 disables the liveness check, as for the in-engine watchdog).
    router_health_poll_s: float = 0.2
    # Auto-drain threshold: a replica whose engine_recoveries counter
    # (the PR 3 degrade path firing repeatedly — a flaky-but-alive
    # engine) reaches this is gracefully drained and recycled. 0 = off.
    router_drain_recoveries: int = 0
    # Admission-side request size cap: a request whose estimated prompt
    # tokens (longest suffix included) plus its max_new_tokens budget
    # exceeds this is rejected at SUBMIT time with a typed
    # RequestTooLarge — instead of first failing at allocation inside
    # the wave (where an oversized request's MemoryError previously
    # aborted the whole wave it joined). 0 = off.
    max_request_tokens: int = 0
    # Speculative decoding on the serving path (docs/speculative.md):
    # each in-flight request carries its own prompt-lookup draft stream,
    # and every decode sweep verifies all drafts batch-wide in ONE
    # K+1-slot pass (runtime/decode.SpecVerifier) — a sweep costs the
    # same whether it advances each request by 1 token or by k accepted
    # tokens, so acceptance multiplies tokens-per-sweep directly. Output
    # stays greedy-exact (token-identical to speculative_k=0, which
    # remains the default and the non-speculative fast path). Composes
    # with sched preemption (draft state truncates to the resume
    # watermark; resume tokens fold into the draft context), prefix
    # coalescing (coalesced entries draft per-suffix), and the fleet
    # (re-dispatch restarts generation, greedy-exact either way).
    speculative_k: int = 0
    # --- resident draft model + adaptive k (runtime/draft.py,
    # serve/spec.py; docs/speculative.md) -------------------------------
    # Checkpoint directory of a SMALL draft model pinned whole on chip
    # through a dedicated residency tier ("" = off, keep prompt-lookup
    # drafting). Draft decode runs entirely against the pinned weights:
    # zero bytes added to the per-sweep host→HBM stream. Output stays
    # token-identical whatever the draft model proposes.
    draft_model_path: str = ""
    # Close the loop: adapt per-SLO-class draft depth k from windowed
    # live acceptance (raise while drafts land, shrink while they miss),
    # fund interactive-class rows first, and back k off to 0 as the
    # brownout ladder's first lever (runtime/pressure.py spec_backoff).
    # Requires speculative_k > 0 (the starting k) — the slot budget is
    # provisioned at spec_k_max so k can grow without re-planning waves.
    spec_adaptive: bool = False
    # Adaptive-k bounds: per-class k stays in [spec_k_min, spec_k_max].
    spec_k_min: int = 0
    spec_k_max: int = 8
    # Acceptance window: a class's k moves only after this many observed
    # drafting passes, comparing windowed acceptance against the two
    # thresholds (raise at >= spec_raise_threshold, shrink at
    # <= spec_backoff_threshold; in between holds).
    spec_window: int = 8
    spec_raise_threshold: float = 0.6
    spec_backoff_threshold: float = 0.2
    # Per-pass draft-token budget across the wave (0 = unlimited):
    # rows are funded in strict SLO-class priority order, so under a
    # budget best-effort drafts are the first to go.
    spec_draft_budget: int = 0
    # Multi-tenant sweep scheduler (serve/sched/; --sched* flags): SLO
    # classes with strict priority + sweep-boundary preemption,
    # per-tenant fair queueing and rate limits, prefix coalescing. Off
    # by default — the queue then pops strict FIFO.
    sched: SchedConfig = dataclasses.field(default_factory=SchedConfig)
    # SLO targets + error budgets (obs/slo.py; --slo* flags): per-class
    # p95 TTFT targets, an aggregate token-latency target, and an
    # availability target over the per-class latency streams PR 12
    # exports — burn-rate/remaining-budget gauges (fls_slo_*) plus a
    # journal event (and, armed, an incident bundle) on exhaustion.
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)
    # Closed-loop fleet elasticity + sweep-phase stagger
    # (serve/autoscale.py; --autoscale* flags): an SLO-burn/queue/
    # pressure-driven controller moves the fleet between autoscale.min
    # and autoscale.max with anti-flap hysteresis and hard interlocks,
    # and holds replica sweep offsets at i/N so worst-case admission
    # wait stays sweep/N. Off by default — the fleet stays at
    # ``replicas`` and phases drift free, the pre-autoscale behaviour.
    autoscale: AutoscaleConfig = dataclasses.field(
        default_factory=AutoscaleConfig
    )
    # --- crash-safe serving (serve/wal.py + serve/recovery.py) ---------
    # Durable request WAL directory ("" = off, the default): every
    # admission/progress/terminal transition appends a crc-framed record;
    # after a process death, startup replay re-admits every unfinished
    # request and serves it token-identically (greedy decode replays
    # bit-for-bit). Fleet mode shares ONE log across replicas.
    wal_dir: str = ""
    # WAL durability policy: "always" fsyncs every record; "admit" (the
    # default) fsyncs admission + terminal records only — progress is
    # recomputable, so losing it to a power cut costs re-decode work,
    # never correctness; "never" flushes to the kernel only (full
    # process-crash durability; machine-crash durability delegated to the
    # filesystem). Every record is flushed either way: SIGKILL loses at
    # most the record in flight.
    wal_fsync: str = "admit"
    # Segment rotation threshold (MB): sealed segments whose every
    # mentioned request id is terminal are compacted (deleted).
    wal_max_mb: float = 64.0

    def __post_init__(self) -> None:
        if self.queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        if self.max_wave_requests < 1:
            raise ValueError("max_wave_requests must be >= 1")
        if self.max_active_requests < self.max_wave_requests:
            raise ValueError(
                "max_active_requests must be >= max_wave_requests"
            )
        if self.default_max_new_tokens < 1:
            raise ValueError("default_max_new_tokens must be >= 1")
        if self.default_deadline_s < 0:
            raise ValueError("default_deadline_s must be >= 0")
        if self.idle_poll_s <= 0:
            raise ValueError("idle_poll_s must be > 0")
        if self.stats_interval_s < 0:
            raise ValueError("stats_interval_s must be >= 0")
        if self.watchdog_abort_s < 0:
            raise ValueError("watchdog_abort_s must be >= 0")
        if self.metrics_port is not None and not 0 <= self.metrics_port <= 65535:
            raise ValueError(
                "metrics_port must be in [0, 65535] (or None for off), "
                f"got {self.metrics_port}"
            )
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.router_phase_weight < 0 or self.router_depth_weight < 0:
            raise ValueError(
                "router_phase_weight/router_depth_weight must be >= 0"
            )
        if self.router_health_poll_s <= 0:
            raise ValueError("router_health_poll_s must be > 0")
        if self.router_drain_recoveries < 0:
            raise ValueError("router_drain_recoveries must be >= 0 (0 = off)")
        if self.max_request_tokens < 0:
            raise ValueError("max_request_tokens must be >= 0 (0 = off)")
        if not 0 <= self.speculative_k <= 64:
            raise ValueError(
                "ServeConfig.speculative_k must be in [0, 64], got "
                f"{self.speculative_k}"
            )
        if self.spec_adaptive and self.speculative_k < 1:
            raise ValueError(
                "spec_adaptive requires speculative_k >= 1 (the starting "
                "draft depth)"
            )
        if not 0 <= self.spec_k_min <= self.spec_k_max <= 64:
            raise ValueError(
                "need 0 <= spec_k_min <= spec_k_max <= 64, got "
                f"[{self.spec_k_min}, {self.spec_k_max}]"
            )
        if self.spec_adaptive and not (
            self.spec_k_min <= self.speculative_k <= self.spec_k_max
        ):
            raise ValueError(
                "speculative_k must sit inside [spec_k_min, spec_k_max] "
                f"when spec_adaptive is on, got k={self.speculative_k} "
                f"bounds=[{self.spec_k_min}, {self.spec_k_max}]"
            )
        if self.spec_window < 1:
            raise ValueError("spec_window must be >= 1")
        if not (
            0.0 <= self.spec_backoff_threshold
            <= self.spec_raise_threshold <= 1.0
        ):
            raise ValueError(
                "need 0 <= spec_backoff_threshold <= spec_raise_threshold "
                f"<= 1, got backoff={self.spec_backoff_threshold} "
                f"raise={self.spec_raise_threshold}"
            )
        if self.spec_draft_budget < 0:
            raise ValueError("spec_draft_budget must be >= 0 (0 = unlimited)")
        if self.autoscale.enabled and not (
            self.autoscale.min <= self.replicas <= self.autoscale.max
        ):
            raise ValueError(
                "replicas must sit inside [autoscale.min, autoscale.max] "
                f"when autoscaling is enabled, got replicas={self.replicas} "
                f"bounds=[{self.autoscale.min}, {self.autoscale.max}]"
            )
        if self.wal_fsync not in ("always", "admit", "never"):
            raise ValueError(
                "wal_fsync must be one of 'always'/'admit'/'never', got "
                f"{self.wal_fsync!r}"
            )
        if self.wal_max_mb <= 0:
            raise ValueError("wal_max_mb must be > 0")
