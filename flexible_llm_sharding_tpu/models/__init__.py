"""Model families. The reference supports Llama-family causal LMs via
transformers (``/root/reference/utils.py:101-119``); here the model math is
owned by the framework as pure jit-able JAX functions."""

from flexible_llm_sharding_tpu.models import llama  # noqa: F401
