"""Pure-function Llama, matching HF `LlamaForCausalLM` numerics.

The reference drives transformers' `LlamaDecoderLayer` on a meta-device
skeleton and materialises weights per layer
(``/root/reference/utils.py:109-131``). TPU-first redesign (SURVEY.md §7):
layers are *pure functions* over parameter pytrees — nothing is ever
"installed" into a module; weights are arguments, so streaming a layer is
just passing a different pytree, and XLA compiles one program per shape
family that is reused for all layers.

Three forward entry points:

- :func:`prefix_suffix_layer` — the streaming scorer step for one prompt:
  prefix runs once producing its KV, all suffix continuations attend to the
  shared prefix KV in one batched call. This is the reference's prefix-KV
  expand trick (``/root/reference/utils.py:266-279``) as a single fused
  jittable function.
- :func:`decoder_layer` — a plain batched layer (monolithic forward /
  training path).
- :func:`forward_full` — whole-model forward for golden tests and training.

Parameter pytree layout (all linear kernels stored [in, out], i.e. the
transpose of HF's [out, in], so matmuls need no transposes on device):

    params = {
      'embed':  {'embedding': [V, D]},
      'layers': [ per-layer dicts ... ]     # or stacked with leading axis
      'norm':   {'scale': [D]},
      'lm_head': {'kernel': [D, V]},        # absent if tied embeddings
    }
    layer = {
      'input_layernorm': {'scale': [D]},
      'post_attention_layernorm': {'scale': [D]},
      'attn': {'wq': [D, nq*hd], 'wk': [D, nkv*hd],
               'wv': [D, nkv*hd], 'wo': [nq*hd, D]},
      'mlp':  {'gate': [D, F], 'up': [D, F], 'down': [F, D]},
    }
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from flexible_llm_sharding_tpu.config import SUPPORTED_ACTIVATIONS, LlamaConfig
from flexible_llm_sharding_tpu.ops import (
    apply_rope,
    apply_rope_interleaved,
    attention,
    rms_norm,
    rope_cos_sin,
)
from flexible_llm_sharding_tpu.ops import pallas_attention
from flexible_llm_sharding_tpu.ops.attention import (
    causal_mask,
    decode_attention,
    prefix_shared_attention,
)

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Projections
# ---------------------------------------------------------------------------

# HIGHEST is a no-op for bf16/fp16 operands (the production dtype — MXU native)
# but keeps float32 matmuls genuinely float32: XLA's default otherwise lowers
# fp32 matmuls to reduced precision, which breaks HF-numerics parity.
_PRECISION = jax.lax.Precision.HIGHEST


def _mm(x: jax.Array, w: jax.Array) -> jax.Array:
    return jnp.matmul(x, w.astype(x.dtype), precision=_PRECISION)


def _lin(x: jax.Array, params: Params, w: str, b: str) -> jax.Array:
    """Linear with optional bias. Bias keys exist only when the model family
    uses them (Qwen2 q/k/v, Llama attention_bias/mlp_bias) — presence is a
    trace-time structural fact, so unbiased models pay nothing."""
    y = _mm(x, params[w])
    if b in params:
        y = y + params[b].astype(y.dtype)
    return y


def _qkv(attn: Params, cfg: LlamaConfig, x: jax.Array):
    """x: [..., L, D] -> q [..., L, n_q, hd], k/v [..., L, n_kv, hd]."""
    hd = cfg.head_dim
    q = _lin(x, attn, "wq", "bq").reshape(*x.shape[:-1], cfg.num_attention_heads, hd)
    k = _lin(x, attn, "wk", "bk").reshape(*x.shape[:-1], cfg.num_key_value_heads, hd)
    v = _lin(x, attn, "wv", "bv").reshape(*x.shape[:-1], cfg.num_key_value_heads, hd)
    if "q_norm" in attn:
        # Per-head-dim RMSNorm on q/k, pre-RoPE (Qwen3 llama-style; Gemma3
        # (1+w)-style — the family's norm_unit_offset covers both).
        q = rms_norm(q, attn["q_norm"], cfg.rms_norm_eps, cfg.norm_unit_offset)
        k = rms_norm(k, attn["k_norm"], cfg.rms_norm_eps, cfg.norm_unit_offset)
    return q, k, v


def _out_proj(attn: Params, o: jax.Array) -> jax.Array:
    """o: [..., L, n_q, hd] -> [..., L, D]."""
    return _lin(o.reshape(*o.shape[:-2], -1), attn, "wo", "bo")


def _qkv_mla(attn: Params, cfg: LlamaConfig, x: jax.Array, positions, total_len=None):
    """Multi-head latent attention q/k/v assembly (DeepSeek-V2/V3,
    DeepseekV3Attention): queries optionally LoRA'd (q_a -> norm -> q_b),
    KV compressed to ``kv_lora_rank`` channels plus ONE shared
    ``qk_rope_head_dim`` rope key, decompressed per head (kv_b) into
    ``qk_nope_head_dim`` keys and ``v_head_dim`` values. Rope applies only
    to the rot slices (interleaved complex-pair convention when
    ``cfg.rope_interleaved``); the shared rope key broadcasts across heads.
    Returns q/k [..., L, H, qk_nope+qk_rope], v [..., L, H, v_head_dim] —
    the downstream attention ops are head-dim-agnostic, so the usual GQA
    machinery runs unchanged with n_kv == n_heads.
    """
    if cfg.rope_local_theta is not None or cfg.layer_rope is not None:
        # No named family composes MLA with per-layer rope bases or NoPE
        # patterns; silently applying one global base would drop declared
        # numerics — fail loudly instead.
        raise NotImplementedError(
            "MLA does not compose with rope_local_theta / layer_rope"
        )
    nh = cfg.num_attention_heads
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    dv = cfg.v_dim
    eps = cfg.rms_norm_eps
    if "q_a" in attn:
        q = _mm(
            rms_norm(_lin(x, attn, "q_a", "bq_a"), attn["q_a_norm"], eps, False),
            attn["q_b"],
        )
    else:
        q = _mm(x, attn["wq"])  # HF's dense q_proj is bias-free
    q = q.reshape(*x.shape[:-1], nh, dn + dr)
    ckv = _lin(x, attn, "kv_a", "bkv_a")  # [..., L, kv_lora + dr]
    c_kv, k_rot = ckv[..., : cfg.kv_lora_rank], ckv[..., cfg.kv_lora_rank :]
    kv = _mm(
        rms_norm(c_kv, attn["kv_a_norm"], eps, False), attn["kv_b"]
    ).reshape(*x.shape[:-1], nh, dn + dv)
    k_nope, v = kv[..., :dn], kv[..., dn:]

    cos, sin = rope_cos_sin(
        positions, dr, cfg.rope_theta, cfg.rope_scaling_spec, total_len=total_len
    )
    rot = apply_rope_interleaved if cfg.rope_interleaved else apply_rope
    q_rot = rot(q[..., dn:], cos, sin)
    k_rot = rot(k_rot[..., None, :], cos, sin)  # [..., L, 1, dr] shared head
    q = jnp.concatenate([q[..., :dn], q_rot], axis=-1)
    k = jnp.concatenate(
        [k_nope, jnp.broadcast_to(k_rot, (*k_nope.shape[:-1], dr))], axis=-1
    )
    return q, k, v


def positioned_qkv(
    params: Params, cfg: LlamaConfig, h: jax.Array, positions, sliding,
    rope_on, total_len=None,
):
    """Post-rope q/k/v for one layer — the single integration point the
    layer fns share: standard families run _qkv + position_qk; MLA
    (``cfg.kv_lora_rank``) runs its own assembly (partial rope, shared
    rope key, distinct value dim)."""
    if cfg.kv_lora_rank:
        return _qkv_mla(params["attn"], cfg, h, positions, total_len)
    q, k, v = _qkv(params["attn"], cfg, h)
    q, k = position_qk(cfg, q, k, positions, sliding, rope_on, total_len)
    return q, k, v


# MLP gate activations by config.hidden_act; HF's 'gelu' is the exact erf
# form, 'gelu_pytorch_tanh' (gemma) the tanh approximation.
_ACT = {
    "silu": jax.nn.silu,
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
}
assert set(_ACT) == set(SUPPORTED_ACTIVATIONS)  # config validates against this


def _dense_mlp(mlp: Params, x: jax.Array, act) -> jax.Array:
    h = act(_lin(x, mlp, "gate", "bgate")) * _lin(x, mlp, "up", "bup")
    return _lin(h, mlp, "down", "bdown")


def _moe_mlp(mlp: Params, cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    """Mixture-of-experts MLP (Mixtral), HF-parity routing.

    Routing matches ``MixtralSparseMoeBlock``: softmax over ALL experts in
    float32, top-k of those probabilities, renormalised by their sum, cast to
    the input dtype, applied to each expert's FFN output.

    TPU-first compute layout: experts are stacked arrays ``gate/up [E, D, F]``,
    ``down [E, F, D]`` and every expert runs on every token (one batched
    einsum per projection, MXU-shaped) with the combine weights zeroing the
    non-selected experts. In the streaming regime this is the right trade:
    the executor is weight-transfer-bound, the per-token FLOP surplus (E/k)
    rides idle MXU cycles, and there is no gather/scatter or ragged shape for
    XLA to choke on. Under expert parallelism (``layer_specs``) the stacked
    E axis is sharded over the mesh, so each chip computes only its own
    experts and GSPMD inserts one psum for the combine — the reference has no
    MoE at all (dense Llama only, SURVEY.md §2.2 'EP: absent').
    """
    e, k = cfg.num_local_experts, cfg.num_experts_per_tok
    logits = _mm(x, mlp["router"])  # [..., L, E], model dtype (HF gate dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, k)  # sorted desc, like torch.topk
    if cfg.moe_norm_topk_prob:  # Mixtral always; Qwen3-MoE per norm_topk_prob
        top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    # Scatter the k renormalised weights back onto the E axis.
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, e, dtype=jnp.float32) * top_vals[..., None], axis=-2
    ).astype(x.dtype)  # [..., L, E]
    h = _ACT[cfg.hidden_act](
        jnp.einsum("...ld,edf->...lef", x, mlp["gate"].astype(x.dtype), precision=_PRECISION)
    ) * jnp.einsum("...ld,edf->...lef", x, mlp["up"].astype(x.dtype), precision=_PRECISION)
    # Fold the combine weights in BEFORE the down projection (scalar per
    # token-expert, so algebraically identical to HF's weight-after-w2) and
    # hard-zero non-selected experts with `where`: a plain `h * 0` would turn
    # an fp16 overflow (inf) in an expert the router never picked into NaN —
    # a failure HF can't have, since it never computes unselected experts.
    # This also avoids materialising a [..., L, E, D] per-expert output.
    c = combine[..., None]  # [..., L, E, 1]
    h = jnp.where(c != 0, h * c, jnp.zeros_like(h))
    return jnp.einsum("...lef,efd->...ld", h, mlp["down"].astype(x.dtype), precision=_PRECISION)


def _llama4_moe_mlp(mlp: Params, cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    """Llama4's MoE: shared expert + top-k routed experts whose INPUT is
    scaled by the sigmoid of the routed logit (HF Llama4TextMoe/Llama4Router:
    top-k logits scattered into -inf, sigmoid in fp32, multiplied into the
    hidden states BEFORE the expert FFN — unlike Mixtral's output weighting).
    Same compute-all einsum layout as the Mixtral path; zero-scaled expert
    inputs are hard-zeroed so they can't overflow."""
    e, k = cfg.num_local_experts, cfg.num_experts_per_tok
    act = _ACT[cfg.hidden_act]
    logits = _mm(x, mlp["router"])  # [..., L, E]
    top_vals, top_idx = jax.lax.top_k(logits.astype(jnp.float32), k)
    c = jnp.sum(
        jax.nn.one_hot(top_idx, e, dtype=jnp.float32)
        * jax.nn.sigmoid(top_vals)[..., None],
        axis=-2,
    ).astype(x.dtype)  # [..., L, E]
    xin = x[..., None, :] * c[..., None]  # [..., L, E, D]
    xin = jnp.where(c[..., None] != 0, xin, jnp.zeros_like(xin))
    h = act(
        jnp.einsum("...led,edf->...lef", xin, mlp["gate"].astype(x.dtype), precision=_PRECISION)
    ) * jnp.einsum("...led,edf->...lef", xin, mlp["up"].astype(x.dtype), precision=_PRECISION)
    routed = jnp.einsum(
        "...lef,efd->...ld", h, mlp["down"].astype(x.dtype), precision=_PRECISION
    )  # contracts e AND f: sums the experts
    shared = _mm(
        act(_mm(x, mlp["shared_gate"])) * _mm(x, mlp["shared_up"]), mlp["shared_down"]
    )
    return shared + routed


def _deepseek_moe_mlp(mlp: Params, cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    """DeepSeek-V3 MoE (DeepseekV3MoE/TopkRouter): fp32 sigmoid scores;
    SELECTION adds a trained correction bias and is group-limited (experts
    partition into n_group groups, each scored by its top-2 sum, only the
    best topk_group groups stay eligible) — the combine WEIGHTS come from
    the unbiased scores, renormalised (+1e-20) iff norm_topk_prob and
    scaled by routed_scaling_factor. A shared expert
    (n_shared_experts x the routed width) adds unconditionally. Same
    compute-all stacked-einsum layout as the Mixtral path."""
    e, k = cfg.num_local_experts, cfg.num_experts_per_tok
    g = cfg.moe_n_group
    logits = jnp.einsum(
        "...ld,de->...le",
        x.astype(jnp.float32),
        mlp["router"].astype(jnp.float32),
        precision=_PRECISION,
    )  # HF routes in float32 end to end
    scores = jax.nn.sigmoid(logits)  # [..., L, E]
    choice = scores + mlp["correction_bias"].astype(jnp.float32)
    if g > 1:
        grouped = choice.reshape(*choice.shape[:-1], g, e // g)
        top2, _ = jax.lax.top_k(grouped, 2)
        group_scores = top2.sum(axis=-1)  # [..., L, G]
        _, gidx = jax.lax.top_k(group_scores, cfg.moe_topk_group)
        gmask = jnp.sum(
            jax.nn.one_hot(gidx, g, dtype=choice.dtype), axis=-2
        )  # [..., L, G]
        choice = jnp.where(
            jnp.repeat(gmask, e // g, axis=-1) > 0, choice, 0.0
        )
    _, top_idx = jax.lax.top_k(choice, k)
    top_w = jnp.take_along_axis(scores, top_idx, axis=-1)
    if cfg.moe_norm_topk_prob:
        top_w = top_w / (jnp.sum(top_w, axis=-1, keepdims=True) + 1e-20)
    top_w = top_w * cfg.moe_routed_scaling_factor
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, e, dtype=jnp.float32) * top_w[..., None],
        axis=-2,
    ).astype(x.dtype)  # [..., L, E]
    act = _ACT[cfg.hidden_act]
    h = act(
        jnp.einsum("...ld,edf->...lef", x, mlp["gate"].astype(x.dtype), precision=_PRECISION)
    ) * jnp.einsum("...ld,edf->...lef", x, mlp["up"].astype(x.dtype), precision=_PRECISION)
    c = combine[..., None]
    h = jnp.where(c != 0, h * c, jnp.zeros_like(h))
    routed = jnp.einsum(
        "...lef,efd->...ld", h, mlp["down"].astype(x.dtype), precision=_PRECISION
    )
    shared = _mm(
        act(_mm(x, mlp["shared_gate"])) * _mm(x, mlp["shared_up"]),
        mlp["shared_down"],
    )
    return routed + shared


def _mlp(mlp: Params, x: jax.Array, cfg: LlamaConfig | None = None) -> jax.Array:
    if "correction_bias" in mlp:
        assert cfg is not None and cfg.num_local_experts > 0
        return _deepseek_moe_mlp(mlp, cfg, x)
    if "shared_gate" in mlp:
        assert cfg is not None and cfg.num_local_experts > 0
        return _llama4_moe_mlp(mlp, cfg, x)
    if "router" in mlp:
        assert cfg is not None and cfg.num_local_experts > 0
        return _moe_mlp(mlp, cfg, x)
    return _dense_mlp(mlp, x, _ACT[cfg.hidden_act if cfg is not None else "silu"])


def _residual_attn(params: Params, cfg: LlamaConfig, x: jax.Array, attn_out) -> jax.Array:
    """Residual add of the attention sublayer. Gemma2's sandwich layout
    (``ffw_sandwich_norms``) norms the sublayer OUTPUT before the add."""
    y = _out_proj(params["attn"], attn_out)
    if cfg.ffw_sandwich_norms:
        y = rms_norm(
            y,
            params["post_attention_layernorm"]["scale"],
            cfg.rms_norm_eps,
            cfg.norm_unit_offset,
        )
    return x + y


def _residual_mlp(params: Params, cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    """Residual add of the MLP sublayer. Standard layout norms the input
    with post_attention_layernorm; Gemma2 norms input AND output with the
    pre/post_feedforward_layernorms."""
    pre = (
        "pre_feedforward_layernorm"
        if cfg.ffw_sandwich_norms
        else "post_attention_layernorm"
    )
    h = rms_norm(x, params[pre]["scale"], cfg.rms_norm_eps, cfg.norm_unit_offset)
    y = _mlp(params["mlp"], h, cfg)
    if cfg.ffw_sandwich_norms:
        y = rms_norm(
            y,
            params["post_feedforward_layernorm"]["scale"],
            cfg.rms_norm_eps,
            cfg.norm_unit_offset,
        )
    return x + y


def layer_sliding_pattern(cfg: LlamaConfig) -> tuple[bool, ...]:
    """Per-layer local-attention flags, one per decoder layer: the explicit
    pattern (Gemma2/Llama4 alternation) or the uniform on/off of the
    configured local form (sliding_window / attention_chunk_size)."""
    if cfg.layer_sliding is not None:
        return cfg.layer_sliding
    local = cfg.sliding_window is not None or cfg.attention_chunk_size is not None
    return (local,) * cfg.num_hidden_layers


def _l2_norm(x: jax.Array, eps: float = 1e-6) -> jax.Array:
    """Llama4's weightless L2 norm (Llama4TextL2Norm): fp32 rsqrt-mean-square,
    cast back — applied to q/k AFTER rope on rope layers."""
    x32 = x.astype(jnp.float32)
    return (x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), -1, keepdims=True) + eps)).astype(x.dtype)


def position_qk(cfg: LlamaConfig, q, k, positions, sliding, rope_on, total_len=None):
    """Apply the per-layer position treatment to fresh q/k heads.

    Standard families: rope at ``positions`` (per-layer base via ``sliding``,
    gemma3). Llama4 adds: per-layer NoPE (``rope_on`` False/traced-False
    layers keep q/k un-rotated), a weightless L2 norm on q/k after rope
    (rope layers only), and temperature-tuned queries on NoPE layers
    (q *= log(floor((pos+1)/floor)+1)*coef + 1). ``rope_on`` follows the
    sliding convention: None = always on, python bool = static, traced
    scalar = selected inside the scan program. ``total_len`` (longrope
    only): real sequence length for the long/short table choice — see
    ops/rope.py rope_cos_sin.
    """
    cos, sin = rope_for_layer(cfg, positions, sliding, total_len)
    rot = apply_rope_interleaved if cfg.rope_interleaved else apply_rope
    q_r, k_r = rot(q, cos, sin), rot(k, cos, sin)
    if cfg.qk_l2_norm:
        # HF builds Llama4TextL2Norm with config.rms_norm_eps.
        q_r = _l2_norm(q_r, cfg.rms_norm_eps)
        k_r = _l2_norm(k_r, cfg.rms_norm_eps)
    if rope_on is None or rope_on is True:
        return q_r, k_r
    if cfg.attn_temperature_tuning:
        # HF Llama4: scales = log(floor((pos+1)/floor_scale)+1)*coef + 1,
        # fp32, applied to the (un-rotated) NoPE queries.
        pos = jnp.asarray(positions, jnp.float32)
        temp = (
            jnp.log(jnp.floor((pos + 1.0) / cfg.attn_floor_scale) + 1.0)
            * cfg.attn_scale_coef
            + 1.0
        )[..., None, None]
        q_n = (q.astype(jnp.float32) * temp).astype(q.dtype)
    else:
        q_n = q
    if rope_on is False:
        return q_n, k
    return (
        jnp.where(rope_on, q_r, q_n),
        jnp.where(rope_on, k_r, k),
    )


def layer_rope_pattern(cfg: LlamaConfig) -> tuple[bool, ...]:
    """Per-layer rope flags (True = rotary applied); all-on when unset."""
    if cfg.layer_rope is not None:
        return cfg.layer_rope
    return (True,) * cfg.num_hidden_layers


def rope_for_layer(cfg: LlamaConfig, positions: jax.Array, sliding, total_len=None):
    """cos/sin for one layer. Gemma3 gives sliding (local) layers their own
    UNSCALED rope base while full (global) layers use rope_theta +
    rope_scaling; other families have a single base. ``sliding`` follows the
    layer-fn convention: None = uniform per cfg, python bool = static
    per-layer choice, traced bool = select between the two static tables
    (both tiny) inside the scan program. ``total_len``: longrope's dynamic
    long/short selector (only the scaled global table uses it)."""
    if cfg.rope_local_theta is None:
        return rope_cos_sin(
            positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling_spec,
            total_len=total_len,
        )
    cos_g, sin_g = rope_cos_sin(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling_spec,
        total_len=total_len,
    )
    cos_l, sin_l = rope_cos_sin(positions, cfg.head_dim, cfg.rope_local_theta, None)
    if sliding is None:
        sliding = cfg.sliding_window is not None
    if isinstance(sliding, bool):
        return (cos_l, sin_l) if sliding else (cos_g, sin_g)
    return jnp.where(sliding, cos_l, cos_g), jnp.where(sliding, sin_l, sin_g)


def _effective_window(cfg: LlamaConfig, sliding) -> tuple[int | None, int | None, Any]:
    """Resolve (window, chunk, sliding) for one layer.

    ``sliding``: None = uniform (the cfg local form applies as-is); a python
    bool = static per-layer toggle (folds into the trace); a traced bool
    scalar = dynamic toggle (Gemma2/Llama4 layers under one scan program).
    Exactly one of window (Mistral-style band) and chunk (Llama4 chunked
    attention) can be set; both local forms share the toggle machinery.
    """
    window, chunk = cfg.sliding_window, cfg.attention_chunk_size
    if (window is None and chunk is None) or sliding is None:
        return window, chunk, None
    if isinstance(sliding, bool):
        if not sliding:
            return None, None, None
        return window, chunk, None
    return window, chunk, sliding


# ---------------------------------------------------------------------------
# Layers
# ---------------------------------------------------------------------------

def embed(
    params: Params, ids: jax.Array, dtype: jnp.dtype, cfg: LlamaConfig | None = None
) -> jax.Array:
    """Token ids [..., L] -> hidden states [..., L, D].

    Gemma (``cfg.embed_scale``) multiplies by sqrt(hidden_size), with the
    normalizer itself rounded to the compute dtype first (HF PR #29402 —
    sqrt(3072) becomes 55.5 in fp16, reproduced for parity)."""
    x = params["embedding"].astype(dtype)[ids]
    if cfg is not None and cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden_size**0.5, dtype)
    return x


def decoder_layer(
    params: Params,
    cfg: LlamaConfig,
    x: jax.Array,
    positions: jax.Array,
    mask: jax.Array | None,
    sliding=None,
    rope_on=None,
    total_len=None,
) -> jax.Array:
    """Plain decoder layer. x: [..., L, D]; positions int [..., L] or [L];
    mask broadcastable to [..., L, L] (caller bakes any local mask in;
    ``sliding``/``rope_on`` select the per-layer rope base / NoPE;
    ``total_len`` is longrope's real-length selector)."""
    h = rms_norm(x, params["input_layernorm"]["scale"], cfg.rms_norm_eps, cfg.norm_unit_offset)
    q, k, v = positioned_qkv(params, cfg, h, positions, sliding, rope_on, total_len)
    attn_out = attention(
        q, k, v, mask, scale=cfg.attn_scale, softcap=cfg.attn_logit_softcap
    )
    x = _residual_attn(params, cfg, x, attn_out)
    return _residual_mlp(params, cfg, x)


def _flash_tp_causal(mesh, q, k, v, plen, local_on, kw):
    """flash_causal_attention under tensor parallelism: shard_map over the
    (embarrassingly parallel) heads axis — pallas_call has no GSPMD
    partitioning rule, so the kernel runs per-shard on each chip's head
    slice. GQA ratios survive the split (both head counts divide by tp)."""
    from jax.sharding import PartitionSpec as P

    flag = jnp.asarray(True if local_on is None else local_on)
    h = P(None, "tp", None)
    f = lambda q, k, v, plen, flag: pallas_attention.flash_causal_attention(
        q, k, v, plen, local_on=flag, **kw
    )
    return jax.shard_map(
        f, mesh=mesh, in_specs=(h, h, h, P(), P()), out_specs=h,
        check_vma=False,
    )(q, k, v, plen, flag)


def _flash_tp_prefix_shared(mesh, qs, kp, vp, ks, vs, plen, local_on, kw):
    """flash_prefix_shared_attention under tensor parallelism (see
    ``_flash_tp_causal``)."""
    from jax.sharding import PartitionSpec as P

    flag = jnp.asarray(True if local_on is None else local_on)
    hq = P(None, None, "tp", None)  # [S, Ls, heads, hd]
    hp = P(None, "tp", None)  # [Lp, kv_heads, hd]
    f = lambda qs, kp, vp, ks, vs, plen, flag: (
        pallas_attention.flash_prefix_shared_attention(
            qs, kp, vp, ks, vs, plen, local_on=flag, **kw
        )
    )
    return jax.shard_map(
        f, mesh=mesh, in_specs=(hq, hp, hp, hq, hq, P(), P()), out_specs=hq,
        check_vma=False,
    )(qs, kp, vp, ks, vs, plen, flag)


def _flash_tp_decode(mesh, q, kp, vp, ks, vs, kg, vg, plen, eos, t, local_on, kw):
    """flash_decode_attention under tensor parallelism (see
    ``_flash_tp_causal``): heads are embarrassingly parallel, so the kernel
    runs per head-shard inside a shard_map; replicated KV inputs reshard to
    the head split at entry."""
    from jax.sharding import PartitionSpec as P

    flag = jnp.asarray(True if local_on is None else local_on)
    hq = P(None, None, "tp", None)  # [S, 1, heads, hd]
    hp = P(None, "tp", None)  # [Lp, kv_heads, hd]
    hs = P(None, None, "tp", None)  # [S, L, kv_heads, hd]
    f = lambda q, kp, vp, ks, vs, kg, vg, plen, eos, t, flag: (
        pallas_attention.flash_decode_attention(
            q, kp, vp, ks, vs, kg, vg, plen, eos, t, local_on=flag, **kw
        )
    )
    return jax.shard_map(
        f,
        mesh=mesh,
        in_specs=(hq, hp, hp, hs, hs, hs, hs, P(), P(), P(), P()),
        out_specs=hq,
        check_vma=False,
    )(q, kp, vp, ks, vs, kg, vg, plen, eos, t, flag)


def prefix_suffix_layer(
    params: Params,
    cfg: LlamaConfig,
    prefix_h: jax.Array,
    suffix_h: jax.Array,
    prefix_len: jax.Array,
    use_pallas: bool = False,
    return_kv: bool = False,
    sliding=None,
    rope_on=None,
    tp_mesh=None,
    total_len=None,
) -> tuple[jax.Array, ...]:
    """One decoder layer over a (prefix, suffixes) prompt — the streaming hot op.

    prefix_h: [Lp, D] right-padded to the Lp bucket; only the first
        ``prefix_len`` positions are real.
    suffix_h: [S, Ls, D], right-padded suffix continuations.
    prefix_len: int32 scalar (dynamic value; shapes stay static).
    total_len: longrope only — the prompt's real total length (prefix +
        longest suffix), an int32 scalar selecting the long/short table
        for BOTH the shared prefix KV and the suffixes. The executor
        rejects prompts whose suffixes straddle the original_max boundary
        (mixed regimes would need the shared prefix KV rotated per
        suffix, defeating the prefix-sharing trick).

    Semantics match the reference exactly (``/root/reference/utils.py:270-279``):
    the prefix runs a causal self-attention once and its (post-RoPE) KV is
    shared across all S suffixes; each suffix token attends to every real
    prefix position plus causally within its own suffix, at rotary positions
    ``prefix_len + i``.

    ``use_pallas`` (static) swaps both attention ops for the Pallas flash
    kernels (ops/pallas_attention.py) when the shapes are eligible — same
    semantics, no [Lq, Lk] score materialisation.
    """
    lp, _ = prefix_h.shape
    s, ls, _ = suffix_h.shape
    eps = cfg.rms_norm_eps
    rope_sliding = sliding  # rope base selection survives the window shortcut
    window, chunk, sliding = _effective_window(cfg, sliding)
    if (window is not None and lp + ls <= window) or (
        chunk is not None and lp + ls <= chunk
    ):
        # Max query-key distance at these (static) bucket shapes is
        # lp + ls - 1 < window (or every position sits in chunk 0): the
        # local mask equals full causal, so drop it — keeping the flash
        # kernels eligible (the common case for Mistral's 4096 window and
        # Llama4's 8192 chunks under the 4096 token cap).
        window = chunk = sliding = None
    # The flash kernels carry the full family surface — custom scale
    # (query_pre_attn_scalar), softcap, sliding window / chunked masks, and
    # the traced per-layer local toggle; NoPE/temperature handling lives in
    # position_qk, OUTSIDE the attention op. Only shape eligibility gates
    # them (tiny head dims / ragged buckets fall back to XLA attention;
    # ragged head dims >= 64 like phi3's 96 pad to the lane multiple inside
    # the kernels).
    # Under tensor parallelism (``tp_mesh``) the kernels run per head-shard
    # via shard_map, so eligibility is checked on PER-SHARD head counts.
    tp_size = tp_mesh.shape["tp"] if tp_mesh is not None else 1
    # MLA (kv_lora_rank) rides the flash path too: the scoring kernels
    # carry q/k's head dim and V's own dim independently (QK^T over
    # head_dim, PV over v_dim) — positioned_qkv hands them per-head
    # decompressed K (nope + shared rope key) and V, so the EFFECTIVE kv
    # head count is the attention head count (GQA ratio 1), whatever the
    # config's num_key_value_heads field says.
    n_kv_eff = (
        cfg.num_attention_heads if cfg.kv_lora_rank else cfg.num_key_value_heads
    )
    flash = use_pallas and pallas_attention.supports(
        cfg.num_attention_heads // tp_size,
        n_kv_eff // tp_size,
        cfg.head_dim,
        ls,
        lp,
        v_dim=cfg.v_dim,
    )

    # --- prefix: causal self-attention, keep post-RoPE KV ---
    h = rms_norm(prefix_h, params["input_layernorm"]["scale"], eps, cfg.norm_unit_offset)
    q, k, v = positioned_qkv(
        params, cfg, h, jnp.arange(lp), rope_sliding, rope_on, total_len
    )
    if flash:
        # Rows at i >= prefix_len are padding; the kernel's valid-len mask
        # additionally skips fully-masked KV blocks.
        flash_kw = dict(
            scale=cfg.attn_scale,
            window=window,
            chunk=chunk,
            softcap=cfg.attn_logit_softcap,
        )
        if tp_mesh is not None:
            attn_out = _flash_tp_causal(
                tp_mesh, q, k, v, prefix_len, sliding, flash_kw
            )
        else:
            attn_out = pallas_attention.flash_causal_attention(
                q, k, v, prefix_len, local_on=sliding, **flash_kw
            )
    else:
        if sliding is None:
            mask = causal_mask(lp, lp, window=window, chunk=chunk)
        else:  # traced per-layer toggle: local mask iff this layer is local
            mask = jnp.where(
                sliding,
                causal_mask(lp, lp, window=window, chunk=chunk),
                causal_mask(lp, lp),
            )
        attn_out = attention(
            q, k, v, mask, scale=cfg.attn_scale, softcap=cfg.attn_logit_softcap
        )
    prefix_mid = _residual_attn(params, cfg, prefix_h, attn_out)
    prefix_out = _residual_mlp(params, cfg, prefix_mid)

    # --- suffixes: batched attention over [shared prefix KV ; own causal KV],
    # prefix KV never expanded across suffixes (ops.prefix_shared_attention) ---
    hs = rms_norm(suffix_h, params["input_layernorm"]["scale"], eps, cfg.norm_unit_offset)
    pos_s = prefix_len + jnp.arange(ls)
    qs, ks, vs = positioned_qkv(
        params, cfg, hs, pos_s, rope_sliding, rope_on, total_len
    )

    if flash:
        if tp_mesh is not None:
            attn_s = _flash_tp_prefix_shared(
                tp_mesh, qs, k, v, ks, vs, prefix_len, sliding, flash_kw
            )
        else:
            attn_s = pallas_attention.flash_prefix_shared_attention(
                qs, k, v, ks, vs, prefix_len, local_on=sliding, **flash_kw
            )
    else:
        attn_s = prefix_shared_attention(
            qs,
            k,
            v,
            ks,
            vs,
            prefix_len,
            scale=cfg.attn_scale,
            window=window,
            softcap=cfg.attn_logit_softcap,
            sliding=sliding,
            chunk=chunk,
        )
    suffix_mid = _residual_attn(params, cfg, suffix_h, attn_s)
    suffix_out = _residual_mlp(params, cfg, suffix_mid)
    if return_kv:
        # Post-RoPE KV, reusable across decode steps (runtime/decode.py).
        return prefix_out, suffix_out, {"kp": k, "vp": v, "ks": ks, "vs": vs}
    return prefix_out, suffix_out


def suffix_only_layer(
    params: Params,
    cfg: LlamaConfig,
    kp: jax.Array,
    vp: jax.Array,
    suffix_h: jax.Array,
    prefix_len: jax.Array,
    use_pallas: bool = False,
    sliding=None,
    rope_on=None,
    tp_mesh=None,
    total_len=None,
) -> tuple[jax.Array, dict]:
    """The suffix half of :func:`prefix_suffix_layer`, fed a CACHED prefix KV.

    In ``prefix_suffix_layer`` the suffix stream depends on the prefix only
    through the post-RoPE (k, v) — so when a pooled prefix entry
    (runtime/kvpool.py) already holds those arrays, a same-prefix wave can
    skip the prefix stream entirely and run just this half, bit-identically:
    same norm, same rotary positions ``prefix_len + i``, same shared-prefix
    attention ops, same residual MLP.

    kp/vp: ``[Lp, n_kv, hd]`` / ``[Lp, n_kv, v_dim]`` post-RoPE prefix KV at
        the SAME Lp bucket the entry was prefilled at (positions past
        ``prefix_len`` are the pad tail, masked like always).
    Returns ``(suffix_out, {"ks": ks, "vs": vs})`` — the caller re-attaches
    kp/vp to rebuild the full decode-KV dict.
    """
    lp = kp.shape[0]
    s, ls, _ = suffix_h.shape
    eps = cfg.rms_norm_eps
    rope_sliding = sliding  # rope base selection survives the window shortcut
    window, chunk, sliding = _effective_window(cfg, sliding)
    if (window is not None and lp + ls <= window) or (
        chunk is not None and lp + ls <= chunk
    ):
        # Same shortcut as prefix_suffix_layer: at these bucket shapes the
        # local mask equals full causal, so drop it (keeps flash eligible).
        window = chunk = sliding = None
    tp_size = tp_mesh.shape["tp"] if tp_mesh is not None else 1
    n_kv_eff = (
        cfg.num_attention_heads if cfg.kv_lora_rank else cfg.num_key_value_heads
    )
    flash = use_pallas and pallas_attention.supports(
        cfg.num_attention_heads // tp_size,
        n_kv_eff // tp_size,
        cfg.head_dim,
        ls,
        lp,
        v_dim=cfg.v_dim,
    )

    hs = rms_norm(suffix_h, params["input_layernorm"]["scale"], eps, cfg.norm_unit_offset)
    pos_s = prefix_len + jnp.arange(ls)
    qs, ks, vs = positioned_qkv(
        params, cfg, hs, pos_s, rope_sliding, rope_on, total_len
    )

    if flash:
        flash_kw = dict(
            scale=cfg.attn_scale,
            window=window,
            chunk=chunk,
            softcap=cfg.attn_logit_softcap,
        )
        if tp_mesh is not None:
            attn_s = _flash_tp_prefix_shared(
                tp_mesh, qs, kp, vp, ks, vs, prefix_len, sliding, flash_kw
            )
        else:
            attn_s = pallas_attention.flash_prefix_shared_attention(
                qs, kp, vp, ks, vs, prefix_len, local_on=sliding, **flash_kw
            )
    else:
        attn_s = prefix_shared_attention(
            qs,
            kp,
            vp,
            ks,
            vs,
            prefix_len,
            scale=cfg.attn_scale,
            window=window,
            softcap=cfg.attn_logit_softcap,
            sliding=sliding,
            chunk=chunk,
        )
    suffix_mid = _residual_attn(params, cfg, suffix_h, attn_s)
    suffix_out = _residual_mlp(params, cfg, suffix_mid)
    return suffix_out, {"ks": ks, "vs": vs}


def decode_step_layer(
    params: Params,
    cfg: LlamaConfig,
    x: jax.Array,
    kv: Params,
    prefix_len: jax.Array,
    suffix_eos: jax.Array,
    t: jax.Array,
    sliding=None,
    rope_on=None,
    use_pallas: bool = False,
    tp_mesh=None,
) -> tuple[jax.Array, Params]:
    """One decoder layer for the K NEWEST tokens per suffix, against cached KV.

    The KV-cache decode path (no reference equivalent — its generation loop
    re-streams the full prompt per token, SURVEY.md §3.5). x: [S, K, D]
    (K=1 for plain decode, K=draft+1 for the speculative verify step);
    kv: {'kp','vp' [Lp,n_kv,hd], 'ks','vs' [S,Ls,n_kv,hd],
    'kg','vg' [S,T,n_kv,hd]} with generated-token slots < t filled;
    t: int32 scalar or per-suffix [S] vector — the fed tokens take slots
    ``t..t+K-1`` and rotary positions ``prefix_len + (suffix_eos[s]+1) +
    t(+j)``. Returns (x_out, kv with those slots of kg/vg written).
    ``use_pallas`` (static) swaps the attention for the flash decode kernel
    when eligible (single-token, shared slot) — unlike the XLA op it skips
    prefix-KV blocks past the real prefix length. Under tensor parallelism
    (``tp_mesh``) the kernel runs per head-shard via shard_map.
    """
    eps = cfg.rms_norm_eps
    rope_sliding = sliding
    kq = x.shape[1]
    base = jnp.asarray(t, jnp.int32)
    h = rms_norm(x, params["input_layernorm"]["scale"], eps, cfg.norm_unit_offset)
    pos = (
        prefix_len + suffix_eos + 1 + jnp.broadcast_to(base, suffix_eos.shape)
    )[:, None] + jnp.arange(kq)[None, :]  # [S, K]
    # longrope's per-suffix real length at this step (the fed tokens'
    # last position + 1). DecodeGenerator rejects generations that CROSS
    # the original_max boundary (parked KV would need re-rotation), so
    # within one generation this always lands on one side.
    total_len = pos[:, -1] + 1 if cfg.rope_scaling_kind == "longrope" else None
    q, k_new, v_new = positioned_qkv(
        params, cfg, h, pos, rope_sliding, rope_on, total_len
    )  # [S, K, n, hd]

    kv = dict(kv)
    if base.ndim == 0:
        kv["kg"] = jax.lax.dynamic_update_slice_in_dim(kv["kg"], k_new, base, axis=1)
        kv["vg"] = jax.lax.dynamic_update_slice_in_dim(kv["vg"], v_new, base, axis=1)
    else:
        # Speculative passes: each suffix writes its K slots at its OWN
        # offset (suffixes accept different draft counts, so their slot
        # clocks drift apart).
        upd = jax.vmap(
            lambda buf, new, off: jax.lax.dynamic_update_slice_in_dim(
                buf, new, off, axis=0
            )
        )
        kv["kg"] = upd(kv["kg"], k_new, base)
        kv["vg"] = upd(kv["vg"], v_new, base)

    window, chunk, sliding = _effective_window(cfg, sliding)
    tp_size = tp_mesh.shape["tp"] if tp_mesh is not None else 1
    if use_pallas and not cfg.kv_lora_rank and kq == 1 and base.ndim == 0 and pallas_attention.supports_decode(
        cfg.num_attention_heads // tp_size,
        cfg.num_key_value_heads // tp_size,
        cfg.head_dim,
    ):
        flash_kw = dict(
            scale=cfg.attn_scale,
            window=window,
            softcap=cfg.attn_logit_softcap,
            chunk=chunk,
        )
        if tp_mesh is not None:
            attn_out = _flash_tp_decode(
                tp_mesh, q, kv["kp"], kv["vp"], kv["ks"], kv["vs"],
                kv["kg"], kv["vg"], prefix_len, suffix_eos, t, sliding,
                flash_kw,
            )
        else:
            attn_out = pallas_attention.flash_decode_attention(
                q,
                kv["kp"],
                kv["vp"],
                kv["ks"],
                kv["vs"],
                kv["kg"],
                kv["vg"],
                prefix_len,
                suffix_eos,
                t,
                local_on=sliding,
                **flash_kw,
            )
    else:
        attn_out = decode_attention(
            q,
            kv["kp"],
            kv["vp"],
            kv["ks"],
            kv["vs"],
            kv["kg"],
            kv["vg"],
            prefix_len,
            suffix_eos,
            t,
            scale=cfg.attn_scale,
            window=window,
            softcap=cfg.attn_logit_softcap,
            sliding=sliding,
            chunk=chunk,
        )
    mid = _residual_attn(params, cfg, x, attn_out)
    return _residual_mlp(params, cfg, mid), kv


def select_eos_and_norm(
    params: Params, cfg: LlamaConfig, suffix_h: jax.Array, suffix_eos: jax.Array
) -> jax.Array:
    """The reference's ``model.norm`` stage (``/root/reference/utils.py:281-286``):
    keep only the last real token of each suffix, then RMSNorm.

    suffix_h: [S, Ls, D]; suffix_eos: int [S] (index of last non-pad token).
    Returns [S, 1, D].
    """
    last = jnp.take_along_axis(suffix_h, suffix_eos[:, None, None], axis=1)
    return rms_norm(last, params["scale"], cfg.rms_norm_eps, cfg.norm_unit_offset)


def lm_head_scores_multi(
    params: Params, h: jax.Array, softcap: float | None = None
) -> jax.Array:
    """Next-token distributions for EVERY position: h [..., K, D] -> float32
    scores [..., K, V]. The speculative verify step's head (lm_head_scores
    keeps only position 0); same softcap-then-softmax semantics."""
    logits = _mm(h, params["kernel"]).astype(jnp.float32)
    if softcap is not None:
        logits = jnp.tanh(logits / softcap) * softcap
    return jax.nn.softmax(logits, axis=-1)


def lm_head_scores(
    params: Params, suffix_h: jax.Array, softcap: float | None = None
) -> jax.Array:
    """The reference's ``lm_head`` stage (``/root/reference/utils.py:287-290``):
    logits of the kept token, softmax -> next-token distribution.

    suffix_h: [S, 1, D] -> float32 scores [S, V]. ``softcap`` is Gemma2's
    final-logit softcapping, applied before the softmax. One-position slice
    of :func:`lm_head_scores_multi` (softmax is per-position, so slicing
    before or after is equivalent — one head implementation to maintain).
    """
    return lm_head_scores_multi(params, suffix_h, softcap)[:, 0]


# ---------------------------------------------------------------------------
# Whole-model forward (golden tests, training, monolithic path)
# ---------------------------------------------------------------------------

def head_params(params: Params) -> Params:
    """lm_head kernel, honouring tied embeddings (``/root/reference/utils.py:113``)."""
    if "lm_head" in params and params["lm_head"]:
        return params["lm_head"]
    return {"kernel": params["embed"]["embedding"].T}


def forward_full(
    params: Params,
    cfg: LlamaConfig,
    ids: jax.Array,
    dtype: jnp.dtype = jnp.float32,
    total_len=None,
) -> jax.Array:
    """Monolithic causal forward: ids [B, L] -> logits [B, L, V] (float32).

    Used by tests as the reference invariant (sharded layerwise forward must
    equal the monolithic forward) and by the training step. ``total_len``
    (longrope): defaults to L — HF's own batch forward selects the
    long/short table from the padded batch length (max position id + 1),
    so the default reproduces an HF forward on these exact ids.
    """
    b, l = ids.shape
    if total_len is None and cfg.rope_scaling_kind == "longrope":
        total_len = jnp.int32(l)
    x = embed(params["embed"], ids, dtype, cfg)
    positions = jnp.arange(l)
    full = causal_mask(l, l)
    banded = causal_mask(
        l, l, window=cfg.sliding_window, chunk=cfg.attention_chunk_size
    )
    pattern = layer_sliding_pattern(cfg)
    rope_pat = layer_rope_pattern(cfg)
    layers = params["layers"]
    if isinstance(layers, (list, tuple)):
        for i, lp in enumerate(layers):
            x = decoder_layer(
                lp, cfg, x, positions,
                banded if pattern[i] else full,
                sliding=pattern[i], rope_on=rope_pat[i], total_len=total_len,
            )
    else:  # stacked pytree with leading layer axis -> scan (one compile)
        flags = jnp.asarray(pattern)
        rflags = jnp.asarray(rope_pat)

        def body(h, xs):
            layer_params, sl, ro = xs
            mask = jnp.where(sl, banded, full)
            return (
                decoder_layer(
                    layer_params, cfg, h, positions, mask, sliding=sl,
                    rope_on=ro, total_len=total_len,
                ),
                None,
            )

        x, _ = jax.lax.scan(body, x, (layers, flags, rflags))
    x = rms_norm(x, params["norm"]["scale"], cfg.rms_norm_eps, cfg.norm_unit_offset)
    logits = _mm(x, head_params(params)["kernel"]).astype(jnp.float32)
    if cfg.final_logit_softcap is not None:
        logits = jnp.tanh(logits / cfg.final_logit_softcap) * cfg.final_logit_softcap
    return logits


# ---------------------------------------------------------------------------
# Initialisation (tests / training-from-scratch)
# ---------------------------------------------------------------------------

def init_layer_params(rng: jax.Array, cfg: LlamaConfig, dtype=jnp.float32) -> Params:
    d, f, hd = cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    ks = jax.random.split(rng, 14)

    def lin(key, fan_in, fan_out):
        scale = (2.0 / (fan_in + fan_out)) ** 0.5
        return (jax.random.normal(key, (fan_in, fan_out)) * scale).astype(dtype)

    def bias(key, n):
        return (jax.random.normal(key, (n,)) * 0.02).astype(dtype)

    if cfg.kv_lora_rank:
        # MLA (DeepSeek): LoRA'd q when q_lora_rank is set, compressed KV
        # always; wo reads the heads' v_head_dim-wide outputs.
        mks = jax.random.split(ks[0], 6)
        attn = {
            "kv_a": lin(mks[0], d, cfg.kv_lora_rank + cfg.qk_rope_head_dim),
            "kv_a_norm": jnp.ones((cfg.kv_lora_rank,), dtype),
            "kv_b": lin(
                mks[1], cfg.kv_lora_rank, nq * (cfg.qk_nope_head_dim + cfg.v_dim)
            ),
            "wo": lin(ks[3], nq * cfg.v_dim, d),
        }
        if cfg.q_lora_rank:
            attn |= {
                "q_a": lin(mks[2], d, cfg.q_lora_rank),
                "q_a_norm": jnp.ones((cfg.q_lora_rank,), dtype),
                "q_b": lin(mks[3], cfg.q_lora_rank, nq * hd),
            }
        else:
            attn["wq"] = lin(mks[4], d, nq * hd)
        if cfg.attention_in_bias:
            # HF deepseek attention_bias: q_a_proj and kv_a_proj_with_mqa
            # only — the dense q_proj is bias=False unconditionally.
            attn["bkv_a"] = bias(ks[8], cfg.kv_lora_rank + cfg.qk_rope_head_dim)
            if cfg.q_lora_rank:
                attn["bq_a"] = bias(ks[7], cfg.q_lora_rank)
    else:
        attn = {
            "wq": lin(ks[0], d, nq * hd),
            "wk": lin(ks[1], d, nkv * hd),
            "wv": lin(ks[2], d, nkv * hd),
            "wo": lin(ks[3], nq * hd, d),
        }
    if cfg.attention_in_bias and not cfg.kv_lora_rank:
        attn |= {
            "bq": bias(ks[7], nq * hd),
            "bk": bias(ks[8], nkv * hd),
            "bv": bias(ks[9], nkv * hd),
        }
    if cfg.attention_out_bias:
        attn["bo"] = bias(ks[10], d)
    if cfg.qk_norm:
        attn |= {"q_norm": jnp.ones((hd,), dtype), "k_norm": jnp.ones((hd,), dtype)}
    if cfg.num_local_experts:
        e = cfg.num_local_experts

        def elin(key, fan_in, fan_out):
            scale = (2.0 / (fan_in + fan_out)) ** 0.5
            return (jax.random.normal(key, (e, fan_in, fan_out)) * scale).astype(dtype)

        mlp = {
            "router": lin(ks[4], d, e),
            "gate": elin(ks[5], d, f),
            "up": elin(ks[6], d, f),
            "down": elin(ks[11], f, d),
        }
    else:
        mlp = {
            "gate": lin(ks[4], d, f),
            "up": lin(ks[5], d, f),
            "down": lin(ks[6], f, d),
        }
        if cfg.mlp_bias:
            mlp |= {"bgate": bias(ks[11], f), "bup": bias(ks[12], f), "bdown": bias(ks[13], d)}
    out = {
        "input_layernorm": {"scale": jnp.ones((d,), dtype)},
        "post_attention_layernorm": {"scale": jnp.ones((d,), dtype)},
        "attn": attn,
        "mlp": mlp,
    }
    if cfg.ffw_sandwich_norms:
        out["pre_feedforward_layernorm"] = {"scale": jnp.ones((d,), dtype)}
        out["post_feedforward_layernorm"] = {"scale": jnp.ones((d,), dtype)}
    return out


def init_mixed_params(rng: jax.Array, cfg: LlamaConfig, dtype=jnp.float32) -> Params:
    """Random params for a MIXED dense/MoE stack (``cfg.moe_layer_pattern``):
    dense layers at the family's dense width (llama4 ``intermediate_size_mlp``),
    MoE layers with stacked experts — plus llama4's shared expert. Used by
    tests and the multichip dryrun to build the checkpoint structure the
    splitter produces from real llama4/qwen3_moe weights."""
    import dataclasses

    assert cfg.moe_layer_pattern is not None
    dense_cfg = dataclasses.replace(
        cfg,
        model_type="llama",
        num_local_experts=0,
        intermediate_size=cfg.intermediate_size_mlp or cfg.intermediate_size,
        moe_layer_pattern=None,
        intermediate_size_mlp=None,
    )
    moe_cfg = dataclasses.replace(cfg, moe_layer_pattern=None)
    keys = jax.random.split(rng, cfg.num_hidden_layers)
    layers = []
    for i, is_moe in enumerate(cfg.moe_layer_pattern):
        lp = init_layer_params(keys[i], moe_cfg if is_moe else dense_cfg, dtype)
        if is_moe and cfg.model_type in ("llama4_text", "deepseek_v3"):
            d, f = cfg.hidden_size, cfg.intermediate_size
            if cfg.model_type == "deepseek_v3":
                # DeepSeek's shared expert is ONE MLP of n_shared_experts x
                # the routed width (V2 checkpoints: 2x; 0 builds zero-width
                # weights that contribute nothing).
                f *= cfg.n_shared_experts
            ks = jax.random.split(jax.random.fold_in(keys[i], 99), 4)

            def lin(key, fan_in, fan_out):
                scale = (2.0 / (fan_in + fan_out)) ** 0.5
                return (jax.random.normal(key, (fan_in, fan_out)) * scale).astype(dtype)

            lp["mlp"] |= {
                "shared_gate": lin(ks[0], d, f),
                "shared_up": lin(ks[1], d, f),
                "shared_down": lin(ks[2], f, d),
            }
            if cfg.model_type == "deepseek_v3":
                lp["mlp"]["correction_bias"] = (
                    jax.random.normal(ks[3], (cfg.num_local_experts,)) * 0.1
                ).astype(jnp.float32)
        layers.append(lp)
    # embed/norm/lm_head only — a 0-layer view skips building (and then
    # discarding) a full dense layer stack.
    params = init_params(
        jax.random.fold_in(rng, 1),
        dataclasses.replace(dense_cfg, num_hidden_layers=0),
        dtype,
    )
    params["layers"] = layers
    return params


def init_params(rng: jax.Array, cfg: LlamaConfig, dtype=jnp.float32) -> Params:
    keys = jax.random.split(rng, cfg.num_hidden_layers + 2)
    params: Params = {
        "embed": {
            "embedding": (
                jax.random.normal(keys[0], (cfg.vocab_size, cfg.hidden_size)) * 0.02
            ).astype(dtype)
        },
        "layers": [
            init_layer_params(keys[i + 1], cfg, dtype)
            for i in range(cfg.num_hidden_layers)
        ],
        "norm": {"scale": jnp.ones((cfg.hidden_size,), dtype)},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {
            "kernel": (
                jax.random.normal(keys[-1], (cfg.hidden_size, cfg.vocab_size)) * 0.02
            ).astype(dtype)
        }
    return params
