"""Checkpoint preparation and per-layer loading.

The reference ships an offline splitter (``/root/reference/prepare_weights.py:12-49``)
that groups a HF ``pytorch_model.bin`` checkpoint's keys by their first three
dotted components and emits one ``{layer}.safetensors`` per top-level module
(``model.embed_tokens``, ``model.layers.{i}``, ``model.norm``, ``lm_head``),
copying tokenizer/config files alongside. The streaming executor then consumes
those per-layer files one at a time (``/root/reference/utils.py:126-127``).

This module keeps that exact file contract (same names, same grouping rule:
``'.'.join(key.split('.')[:3])``, same incremental shard loading so peak host
RAM stays at a couple of HF shards) and extends it TPU-first:

- Input can be ``.bin`` (torch) or ``.safetensors`` HF checkpoints, indexed or
  single-file.
- Output tensors are stored in the framework's *native layout* — linear
  kernels pre-transposed to [in, out] and renamed to the pytree layout of
  ``models/llama.py`` — so the hot load path is a zero-copy mmap + device_put
  with no host-side transposes. (Layer files produced by the *reference's*
  own ``prepare_weights.py`` — HF key names, [out, in] kernels — also load:
  :func:`load_layer` converts on the fly.)
"""

from __future__ import annotations

import gc
import json
import os
import re
import shutil
from glob import glob
from typing import Any, Callable, Iterator

import numpy as np

try:  # bf16 numpy arrays
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BFLOAT16 = None

from safetensors import safe_open
from safetensors.numpy import load_file as st_load_file
from safetensors.numpy import save_file as st_save_file

from flexible_llm_sharding_tpu.config import LlamaConfig
from flexible_llm_sharding_tpu.integrity import manifest as integrity_manifest

LAYER_FILE_SUFFIX = ".safetensors"
NATIVE_LAYOUT_MARKER = "fls_tpu_layout.json"

# int8 weight compression: a quantized tensor is stored as `{key}` (int8)
# plus `{key}::scale` (float32, one scale per output channel = the last axis
# of the native [in, out] layout); load_layer regroups the pair into a
# {"q8", "s"} leaf-group that the executor dequantizes ON DEVICE after the
# host->HBM transfer — the link carries half the bytes, which is the whole
# point in the transfer-bound streaming regime. Opt-in
# (``split_into_layers(dtype='int8')``), approximate (symmetric per-channel
# round-to-nearest), and self-describing via the layout marker.
QUANT_SCALE_SUFFIX = "::scale"

# int4: two values pack per byte along the IN axis, with GROUP-WISE scales
# along that axis (per-output-channel alone is too coarse at 4 bits; the
# group bounds each weight's error by its neighbours' amax, the standard
# int4 recipe). A quantized tensor stores `{key}` (packed uint8, in/2) +
# `{key}::scale4` (fp32 [.., in/group, out]) and reaches the device as a
# {"q4","s"} leaf-group — HALF of int8's bytes over the host->HBM link,
# the binding constraint of the streaming regime. Tensors whose in-dim
# doesn't divide the group fall back to per-output-channel int8 (the
# ordinary _quantize_int8 layout); the leaves self-describe either way.
QUANT4_SCALE_SUFFIX = "::scale4"
INT4_GROUP = 64


def is_float_like(a) -> bool:
    """True for real float dtypes AND the bfloat16 extension type — the
    ONE spelling of "does this tensor cast/quantize" shared by the
    quantizers, the dtype-kind derivation, and the planner (a second
    spelling drifting on a future fp8 addition is the failure mode)."""
    dt = np.asarray(a).dtype
    return np.issubdtype(dt, np.floating) or dt.name == "bfloat16"


def _quantize_int8(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8: returns (q [same shape], scale).

    2-D [in, out] kernels get one scale per output channel (the last axis).
    3-D [E, in, out] stacked MoE expert kernels get one scale per (expert,
    output channel) — amax over axis 1 only, scale [E, out] — so an expert
    with small weights does not inherit the largest expert's scale (which
    would inflate its quantization error well beyond the dense per-channel
    level)."""
    w32 = np.asarray(w, np.float32)
    reduce_axes = tuple(range(w32.ndim - 1)) if w32.ndim < 3 else (1,)
    amax = np.max(np.abs(w32), axis=reduce_axes)
    scale = np.maximum(amax, 1e-12).astype(np.float32) / 127.0
    q = np.clip(
        np.rint(w32 / scale.reshape(_scale_expand(scale, w32.ndim))), -127, 127
    ).astype(np.int8)
    return q, scale


def _scale_expand(scale: np.ndarray, q_ndim: int):
    """Broadcast shape for a quantization scale against its int8 payload:
    the scale keeps the payload's leading axes (stack/expert) and trailing
    channel axis; the reduced middle axes become size 1. Covers all four
    layouts — stored [out] / stacked [k, out] / per-expert [E, out] /
    stacked-per-expert [k, E, out]."""
    return scale.shape[:-1] + (1,) * (q_ndim - scale.ndim) + scale.shape[-1:]


def _quantize_int4(
    w: np.ndarray, group: int = INT4_GROUP
) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric group-wise int4 along the IN axis (axis -2): values in
    [-7, 7] stored offset-binary (nibble = q + 8), packed two per byte along
    the in axis (low nibble = even index). Returns (packed uint8
    [.., in/2, out], scale fp32 [.., in/group, out]). Callers guarantee
    in % group == 0 (``_quantize_flat`` falls back to int8 otherwise)."""
    w32 = np.asarray(w, np.float32)
    *lead, n_in, n_out = w32.shape
    wg = w32.reshape(*lead, n_in // group, group, n_out)
    amax = np.max(np.abs(wg), axis=-2)
    scale = (np.maximum(amax, 1e-12) / 7.0).astype(np.float32)
    q = np.clip(np.rint(wg / scale[..., None, :]), -7, 7).astype(np.int8)
    q = q.reshape(*lead, n_in, n_out)
    nib = (q + 8).astype(np.uint8)
    return nib[..., 0::2, :] | (nib[..., 1::2, :] << 4), scale


def _quantize_flat(
    sd: dict[str, np.ndarray], dtype: str = "int8"
) -> dict[str, np.ndarray]:
    """Quantize one flat native state dict: matmul kernels (>= 2-D floats)
    quantize and gain a scale twin; 1-D tensors (norm scales, biases) are
    tiny and stay exact in float32. ``dtype`` 'int8' (per-output-channel)
    or 'int4' (group-wise + packed; kernels whose in-dim doesn't fit the
    group fall back to per-output-channel int8 for that tensor — leaves
    self-describe). The single rule shared by split_into_layers and
    requantize_native."""
    qd: dict[str, np.ndarray] = {}
    for k, v in sd.items():
        v = np.asarray(v)
        if v.ndim >= 2 and is_float_like(v):
            if dtype == "int4" and v.shape[-2] % INT4_GROUP == 0:
                q, sc = _quantize_int4(v)
                qd[k] = q
                qd[k + QUANT4_SCALE_SUFFIX] = sc
            else:
                q, sc = _quantize_int8(v)
                qd[k] = q
                qd[k + QUANT_SCALE_SUFFIX] = sc
        elif is_float_like(v) and v.dtype.itemsize < 4:
            # Sub-fp32 floats (bf16, fp16) up-cast EXACTLY to the
            # documented "1-D tensors stay exact in float32" contract —
            # fp16 passing through unchanged silently broke the
            # planner's byte estimates for fp16-source checkpoints.
            qd[k] = np.asarray(v, np.float32)
        else:
            qd[k] = v
    return qd


def is_quantized_leaf(node) -> bool:
    """True for BOTH quantized leaf-groups: int8 {"q8","s"} and int4
    {"q4","s"} — detection sites (loader cast, placement probe) treat them
    alike; kind-specific handling branches on :func:`quant_kind`."""
    return isinstance(node, dict) and set(node) in ({"q8", "s"}, {"q4", "s"})


def quant_kind(node) -> str:
    """'q8' or 'q4' for a quantized leaf-group."""
    return "q8" if "q8" in node else "q4"


def flat_dtype_kind(flat: dict[str, Any]) -> str:
    """Storage-dtype kind of one layer file's flat tensor dict — the ONE
    derivation shared by the manifest writer (``layer_entry`` records it
    per layer) and the load-path check (``load_layer`` compares it), so
    the two can never desync. 'int4' when any group-scale twin is
    present (int8 per-tensor fallbacks inside an int4 file keep the int4
    kind — leaves self-describe), 'int8' for per-channel scales, else
    the dtype name of the layer's largest float tensor ('bfloat16',
    'float32', ...) or 'none' for a float-free file."""
    keys = flat.keys()
    if any(k.endswith(QUANT4_SCALE_SUFFIX) for k in keys):
        return "int4"
    if any(k.endswith(QUANT_SCALE_SUFFIX) for k in keys):
        return "int8"
    best = None
    for k in sorted(keys):
        a = np.asarray(flat[k])
        if is_float_like(a):
            if best is None or a.nbytes > best.nbytes:
                best = a
    return best.dtype.name if best is not None else "none"


def simulate_quantized(a: np.ndarray, dtype: str) -> np.ndarray:
    """Quantize->dequantize round trip of ONE kernel under exactly the
    branch rule ``_quantize_flat`` materializes (int4 falls back to
    per-output-channel int8 when the in-dim is off the group) — float32
    out. The sensitivity probe (runtime/precisionplan.py) scores layers
    through this, so what it measures is what ``requantize_native``
    later writes and ``executor._dequant_tree`` later computes."""
    if dtype not in ("int8", "int4"):
        raise ValueError(f"simulate_quantized: unsupported dtype {dtype!r}")
    a32 = np.asarray(a, np.float32)
    if dtype == "int4" and a32.ndim >= 2 and a32.shape[-2] % INT4_GROUP == 0:
        q, s = _quantize_int4(a32)
        return dequantize_np({"q4": q, "s": s}).astype(np.float32)
    q, s = _quantize_int8(a32)
    return dequantize_np({"q8": q, "s": s}).astype(np.float32)


def dequant4_math(b, s, xp):
    """int4 unpack + group dequant, parameterized on the array module
    (numpy for host oracles, jax.numpy for the on-device kernel) — the
    SINGLE source of truth for the packing convention: low nibble = even
    in-index, offset-binary (nibble = q + 8), scales [.., in/g, out]."""
    lo = (b & 0xF).astype(xp.float32) - 8.0
    hi = (b >> 4).astype(xp.float32) - 8.0
    q = xp.stack([lo, hi], axis=-2)  # [.., in/2, 2, out]
    *lead, half, _, out = q.shape
    q = q.reshape(*lead, half * 2, out)
    n_groups = s.shape[-2]
    qg = q.reshape(*lead, n_groups, (half * 2) // n_groups, out)
    return (qg * s[..., None, :]).reshape(*lead, half * 2, out)


def dequantize_np(node: dict[str, np.ndarray]) -> np.ndarray:
    """Host-side dequantize of one quantized leaf-group (float32)."""
    if quant_kind(node) == "q4":
        return dequant4_math(
            np.asarray(node["q4"], np.uint8),
            np.asarray(node["s"], np.float32),
            np,
        )
    q = np.asarray(node["q8"], np.float32)
    s = np.asarray(node["s"])
    return q * s.reshape(_scale_expand(s, q.ndim))

# ---------------------------------------------------------------------------
# Key grouping — the reference's rule (/root/reference/prepare_weights.py:21)
# ---------------------------------------------------------------------------

def key_to_layer(key: str) -> str:
    """Group a flat HF param key into its top-level layer name.

    Same rule as the reference: strip ``.weight``/``.bias``, keep the first
    three dotted components (``model.layers.17.self_attn.q_proj.weight`` ->
    ``model.layers.17``; ``lm_head.weight`` -> ``lm_head``).
    """
    return ".".join(re.sub(r"\.(weight|bias)$", "", key).split(".")[:3])


def layer_names_for(num_hidden_layers: int, tie_word_embeddings: bool = False) -> list[str]:
    """Execution-ordered layer names (``/root/reference/utils.py:106-107``)."""
    names = (
        ["model.embed_tokens"]
        + [f"model.layers.{i}" for i in range(num_hidden_layers)]
        + ["model.norm"]
    )
    if not tie_word_embeddings:
        names.append("lm_head")
    return names


def layer_file_for(model_path: str, name: str, tied: bool = False) -> str:
    """The file a layer name actually reads: with tied embeddings,
    ``lm_head`` re-materialises from the embedding file. The ONE mapping
    shared by the streaming loader (quarantine keys, stat guards) and the
    residency planner's byte estimates — any change to the on-disk layout
    must keep both views identical or the planner silently desyncs from
    what the loader streams."""
    fname = "model.embed_tokens" if (name == "lm_head" and tied) else name
    return os.path.join(model_path, f"{fname}{LAYER_FILE_SUFFIX}")


# ---------------------------------------------------------------------------
# HF checkpoint enumeration (host side, offline)
# ---------------------------------------------------------------------------

def _hf_weight_map(src_dir: str) -> tuple[dict[str, str], str]:
    """Return ({param_key: shard_filename}, kind) for any HF checkpoint shape."""
    for index_name, kind in (
        ("model.safetensors.index.json", "safetensors"),
        ("pytorch_model.bin.index.json", "bin"),
    ):
        p = os.path.join(src_dir, index_name)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)["weight_map"], kind
    for single, kind in (("model.safetensors", "safetensors"), ("pytorch_model.bin", "bin")):
        p = os.path.join(src_dir, single)
        if os.path.exists(p):
            if kind == "safetensors":
                with safe_open(p, framework="numpy") as f:
                    keys = list(f.keys())
            else:
                import torch

                keys = list(torch.load(p, map_location="meta", weights_only=True).keys())
            return {k: single for k in keys}, kind
    raise FileNotFoundError(f"No HF checkpoint found under {src_dir}")


def _load_shard(path: str, kind: str, want=None) -> dict[str, np.ndarray]:
    """Load one HF shard; ``want`` (key -> bool) selects keys. With
    safetensors unwanted tensors are never READ (multi-GB vision towers of
    a multimodal bundle never touch RAM); the torch format can only filter
    after a full load."""
    if kind == "safetensors":
        if want is None:
            return st_load_file(path)
        out = {}
        with safe_open(path, framework="numpy") as f:
            for k in f.keys():
                if want(k):
                    out[k] = f.get_tensor(k)
        return out
    import torch

    out = {}
    for k, t in torch.load(path, map_location="cpu", weights_only=True).items():
        if want is not None and not want(k):
            continue
        if t.dtype == torch.bfloat16:
            out[k] = t.view(torch.uint16).numpy().view(_BFLOAT16)
        else:
            out[k] = t.numpy()
    return out


# ---------------------------------------------------------------------------
# HF <-> native layout conversion
# ---------------------------------------------------------------------------

# (native flat key, HF sub-key, transpose?) for a decoder layer.
_LAYER_MAP = [
    ("input_layernorm.scale", "input_layernorm.weight", False),
    ("post_attention_layernorm.scale", "post_attention_layernorm.weight", False),
    ("attn.wq", "self_attn.q_proj.weight", True),
    ("attn.wk", "self_attn.k_proj.weight", True),
    ("attn.wv", "self_attn.v_proj.weight", True),
    ("attn.wo", "self_attn.o_proj.weight", True),
    ("mlp.gate", "mlp.gate_proj.weight", True),
    ("mlp.up", "mlp.up_proj.weight", True),
    ("mlp.down", "mlp.down_proj.weight", True),
]

# Bias vectors (1-D, no transpose), present only in some families (Qwen2
# q/k/v; Llama with attention_bias/mlp_bias). Consumed when the checkpoint
# has them, absent from the native file otherwise — models/llama.py treats
# bias presence as a trace-time structural fact.
_LAYER_MAP_OPTIONAL = [
    ("attn.bq", "self_attn.q_proj.bias"),
    ("attn.bk", "self_attn.k_proj.bias"),
    ("attn.bv", "self_attn.v_proj.bias"),
    ("attn.bo", "self_attn.o_proj.bias"),
    ("attn.q_norm", "self_attn.q_norm.weight"),  # qwen3 per-head-dim RMSNorm
    ("attn.k_norm", "self_attn.k_norm.weight"),
    # gemma2 sandwich norms around the MLP
    ("pre_feedforward_layernorm.scale", "pre_feedforward_layernorm.weight"),
    ("post_feedforward_layernorm.scale", "post_feedforward_layernorm.weight"),
    ("mlp.bgate", "mlp.gate_proj.bias"),
    ("mlp.bup", "mlp.up_proj.bias"),
    ("mlp.bdown", "mlp.down_proj.bias"),
]


# Non-parameter buffers that may appear in HF checkpoints and carry no weights.
_IGNORABLE_HF_SUFFIXES = ("rotary_emb.inv_freq",)


def _stack_experts(layer_name, prefix, name_map, sd, out, consumed) -> None:
    """Stack per-expert Linear weights ``{prefix}.{e}.{hf_name}.weight`` into
    one transposed [E, in, out] native array per projection (the _moe_mlp
    einsum layout — one tensor per projection keeps a shard upload a single
    device_put)."""
    probe = name_map[0][1]
    n_exp = 0
    while f"{prefix}.{n_exp}.{probe}.weight" in sd:
        n_exp += 1
    if not n_exp:
        raise ValueError(f"{layer_name}: MoE layer with no expert weights")
    for native_key, hf_w in name_map:
        stack = []
        for ei in range(n_exp):
            key = f"{prefix}.{ei}.{hf_w}.weight"
            stack.append(sd[key].T)
            consumed.add(key)
        out[native_key] = np.ascontiguousarray(np.stack(stack))


def hf_layer_to_native(layer_name: str, sd: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Convert one layer's HF-keyed state dict to native flat keys/layout.

    Projection biases (Qwen2 q/k/v; Llama attention_bias/mlp_bias) map to
    their native slots when present. Tensors with no slot at all (an unknown
    architecture's extras) raise instead of silently dropping.
    """
    if layer_name == "model.embed_tokens":
        return {"embedding": sd["model.embed_tokens.weight"]}
    if layer_name == "model.norm":
        return {"scale": sd["model.norm.weight"]}
    if layer_name == "lm_head":
        return {"kernel": np.ascontiguousarray(sd["lm_head.weight"].T)}
    moe = any(".block_sparse_moe." in k for k in sd)
    qmoe = f"{layer_name}.mlp.experts.0.gate_proj.weight" in sd  # qwen3_moe / deepseek
    fused = f"{layer_name}.self_attn.qkv_proj.weight" in sd  # phi3 layout
    ff = any(".feed_forward." in k for k in sd)  # llama4 naming
    ff_moe = f"{layer_name}.feed_forward.router.weight" in sd
    mla = f"{layer_name}.self_attn.kv_a_proj_with_mqa.weight" in sd  # deepseek
    out = {}
    consumed = set()
    for native_key, hf_sub, transpose in _LAYER_MAP:
        if (moe or ff or qmoe) and native_key.startswith("mlp."):
            continue  # Mixtral / llama4 / qwen3_moe expert layouts below
        if fused and native_key in (
            "attn.wq", "attn.wk", "attn.wv", "mlp.gate", "mlp.up"
        ):
            continue  # carried fused; split below
        if mla and native_key in ("attn.wq", "attn.wk", "attn.wv"):
            continue  # MLA projections mapped below (wq only when dense q)
        key = f"{layer_name}.{hf_sub}"
        w = sd[key]
        consumed.add(key)
        out[native_key] = np.ascontiguousarray(w.T) if transpose else w
    if mla:
        # DeepSeek multi-head latent attention (DeepseekV3Attention):
        # q either dense (q_proj) or LoRA (q_a -> norm -> q_b); KV always
        # compressed (kv_a_proj_with_mqa -> norm -> kv_b). Kernels store
        # [in, out] like every other native projection.
        def take(native_key, hf_sub, transpose=True, optional=False):
            key = f"{layer_name}.self_attn.{hf_sub}"
            if key not in sd:
                if optional:
                    return
                raise KeyError(f"{layer_name}: missing MLA tensor {key}")
            w = sd[key]
            consumed.add(key)
            out[native_key] = np.ascontiguousarray(w.T) if transpose else w

        if f"{layer_name}.self_attn.q_proj.weight" in sd:
            take("attn.wq", "q_proj.weight")
        else:
            take("attn.q_a", "q_a_proj.weight")
            take("attn.q_a_norm", "q_a_layernorm.weight", transpose=False)
            take("attn.q_b", "q_b_proj.weight")
            take("attn.bq_a", "q_a_proj.bias", transpose=False, optional=True)
        take("attn.kv_a", "kv_a_proj_with_mqa.weight")
        take("attn.kv_a_norm", "kv_a_layernorm.weight", transpose=False)
        take("attn.kv_b", "kv_b_proj.weight")
        take("attn.bkv_a", "kv_a_proj_with_mqa.bias", transpose=False, optional=True)
    if fused:
        # Phi3 fuses q/k/v into qkv_proj [(nq+2*nkv)*hd, D] and gate/up into
        # gate_up_proj [2F, D]. The split needs no config: o_proj's input
        # width IS nq*hd, and the two kv blocks share the remainder equally.
        qkv = sd[f"{layer_name}.self_attn.qkv_proj.weight"]
        consumed.add(f"{layer_name}.self_attn.qkv_proj.weight")
        nq_hd = out["attn.wo"].shape[0]  # [nq*hd, D] after transpose
        nkv_hd = (qkv.shape[0] - nq_hd) // 2
        if qkv.shape[0] != nq_hd + 2 * nkv_hd:
            raise ValueError(
                f"{layer_name}: qkv_proj rows {qkv.shape[0]} do not split "
                f"into q={nq_hd} + 2*kv (o_proj implies nq*hd={nq_hd})"
            )
        out["attn.wq"] = np.ascontiguousarray(qkv[:nq_hd].T)
        out["attn.wk"] = np.ascontiguousarray(qkv[nq_hd : nq_hd + nkv_hd].T)
        out["attn.wv"] = np.ascontiguousarray(qkv[nq_hd + nkv_hd :].T)
        gu = sd[f"{layer_name}.mlp.gate_up_proj.weight"]
        consumed.add(f"{layer_name}.mlp.gate_up_proj.weight")
        f_dim = gu.shape[0] // 2
        out["mlp.gate"] = np.ascontiguousarray(gu[:f_dim].T)
        out["mlp.up"] = np.ascontiguousarray(gu[f_dim:].T)
    for native_key, hf_sub in _LAYER_MAP_OPTIONAL:
        if mla and native_key in ("attn.bq", "attn.bk", "attn.bv"):
            continue  # HF MLA projections are bias-free (q_a/kv_a aside)
        key = f"{layer_name}.{hf_sub}"
        if key in sd:
            consumed.add(key)
            out[native_key] = sd[key]
    if ff and not ff_moe:
        # Llama4 dense layer: feed_forward.{gate,up,down}_proj (its dense
        # layers use intermediate_size_mlp, distinct from the experts').
        for native_key, sub in (
            ("mlp.gate", "gate_proj"), ("mlp.up", "up_proj"), ("mlp.down", "down_proj")
        ):
            key = f"{layer_name}.feed_forward.{sub}.weight"
            out[native_key] = np.ascontiguousarray(sd[key].T)
            consumed.add(key)
    if ff_moe:
        # Llama4 MoE layer: experts.gate_up_proj [E, D, 2F] (ALREADY
        # [in, out] per expert — a Parameter, not a Linear) splits into
        # gate/up; experts.down_proj [E, F, D] passes through; router
        # [E, D] and the shared expert's Linears transpose as usual.
        gu = sd[f"{layer_name}.feed_forward.experts.gate_up_proj"]
        consumed.add(f"{layer_name}.feed_forward.experts.gate_up_proj")
        f_dim = gu.shape[-1] // 2
        out["mlp.gate"] = np.ascontiguousarray(gu[..., :f_dim])
        out["mlp.up"] = np.ascontiguousarray(gu[..., f_dim:])
        dk = f"{layer_name}.feed_forward.experts.down_proj"
        out["mlp.down"] = sd[dk]
        consumed.add(dk)
        rk = f"{layer_name}.feed_forward.router.weight"
        out["mlp.router"] = np.ascontiguousarray(sd[rk].T)
        consumed.add(rk)
        for native_key, sub in (
            ("mlp.shared_gate", "gate_proj"),
            ("mlp.shared_up", "up_proj"),
            ("mlp.shared_down", "down_proj"),
        ):
            key = f"{layer_name}.feed_forward.shared_expert.{sub}.weight"
            out[native_key] = np.ascontiguousarray(sd[key].T)
            consumed.add(key)
    if qmoe:
        # Qwen3-MoE / DeepSeek: router at mlp.gate [E, D] -> [D, E];
        # per-expert gate/up/down Linears stack into the same
        # [E, D, F] / [E, F, D] native arrays as Mixtral. DeepSeek adds a
        # routing correction-bias buffer and a shared expert.
        rk = f"{layer_name}.mlp.gate.weight"
        out["mlp.router"] = np.ascontiguousarray(sd[rk].T)
        consumed.add(rk)
        _stack_experts(
            layer_name, f"{layer_name}.mlp.experts",
            (("mlp.gate", "gate_proj"), ("mlp.up", "up_proj"), ("mlp.down", "down_proj")),
            sd, out, consumed,
        )
        bk = f"{layer_name}.mlp.gate.e_score_correction_bias"
        if bk in sd:
            out["mlp.correction_bias"] = sd[bk]
            consumed.add(bk)
        for native_key, sub in (
            ("mlp.shared_gate", "gate_proj"),
            ("mlp.shared_up", "up_proj"),
            ("mlp.shared_down", "down_proj"),
        ):
            key = f"{layer_name}.mlp.shared_experts.{sub}.weight"
            if key in sd:
                out[native_key] = np.ascontiguousarray(sd[key].T)
                consumed.add(key)
    if moe:
        # Mixtral MoE: router [E, D] -> [D, E]; per-expert w1 (gate) / w3
        # (up) [F, D] and w2 (down) [D, F] stack into [E, D, F] / [E, F, D]
        # native arrays (models/llama.py _moe_mlp layout) — one tensor per
        # projection so a shard upload stays a single device_put.
        rk = f"{layer_name}.block_sparse_moe.gate.weight"
        out["mlp.router"] = np.ascontiguousarray(sd[rk].T)
        consumed.add(rk)
        _stack_experts(
            layer_name, f"{layer_name}.block_sparse_moe.experts",
            (("mlp.gate", "w1"), ("mlp.up", "w3"), ("mlp.down", "w2")),
            sd, out, consumed,
        )
    leftover = {
        k for k in sd.keys() - consumed if not k.endswith(_IGNORABLE_HF_SUFFIXES)
    }
    if leftover:
        raise ValueError(
            f"{layer_name}: tensors {sorted(leftover)} have no native-layout slot"
        )
    return out


def native_to_pytree(layer_name: str, flat: dict[str, np.ndarray]) -> dict[str, Any]:
    """Unflatten dotted native keys into the nested pytree of models/llama.py."""
    tree: dict[str, Any] = {}
    for k, v in flat.items():
        node = tree
        parts = k.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def _is_native(sd_keys) -> bool:
    return not any(k.startswith(("model.", "lm_head")) for k in sd_keys)


# ---------------------------------------------------------------------------
# The offline splitter (prepare_weights equivalent)
# ---------------------------------------------------------------------------

# Multimodal wrapper checkpoints (Gemma-3, Llama-4): the published weights
# are usually the vision+text bundle; scoring wants the text tower. The
# splitter extracts it: language-model keys remap to the plain text layout,
# vision/projector keys drop, and the emitted config.json is the nested
# text_config (so the split dir IS a text checkpoint). The wrapper->text
# config rule itself lives in config.extract_text_config, shared with
# LlamaConfig.from_hf_config.
_MM_DROP_PREFIXES = (
    "model.vision_tower.",
    "model.multi_modal_projector.",
    "model.vision_model.",
    "vision_tower.",
    "vision_model.",
    "multi_modal_projector.",
)


def _multimodal_remap(src_dir: str):
    """(remap_fn, text_config dict) for a multimodal wrapper checkpoint, or
    (None, None) for plain text checkpoints. remap_fn: original HF key ->
    text-model key, or None for dropped (vision/projector) keys."""
    from flexible_llm_sharding_tpu.config import extract_text_config

    cfg_path = os.path.join(src_dir, "config.json")
    if not os.path.exists(cfg_path):
        return None, None
    with open(cfg_path) as f:
        d = json.load(f)
    tc = extract_text_config(d)
    if tc is None:
        return None, None

    def remap(k: str):
        for p in _MM_DROP_PREFIXES:
            if k.startswith(p):
                return None
        # transformers >= 4.52 nests the tower as model.language_model.*;
        # older exports used language_model.model.* (+ language_model.lm_head).
        if k.startswith("model.language_model."):
            return "model." + k[len("model.language_model."):]
        if k.startswith("language_model.model."):
            return "model." + k[len("language_model.model."):]
        if k.startswith("language_model.lm_head"):
            return k[len("language_model."):]
        return k  # lm_head.* and any already-plain keys

    return remap, tc


def split_into_layers(
    src_dir: str,
    out_dir: str,
    dtype: str | None = None,
    layout: str = "native",
    progress: Callable[[str], None] | None = None,
) -> list[str]:
    """HF checkpoint dir -> per-layer safetensors files + copied aux files.

    Mirrors ``/root/reference/prepare_weights.py:12-49``: aux (non-weight)
    files copied first; layers emitted in ascending (min shard id, #shards)
    order; HF shards loaded incrementally and freed as their keys are
    consumed, keeping peak RAM to ~a couple of shards.

    dtype: optionally cast (e.g. 'bfloat16' — the TPU-preferred storage type).
    layout: 'native' (pre-transposed, renamed) or 'hf' (reference-identical).
    Returns the ordered list of emitted layer names.
    """
    if layout not in ("native", "hf"):
        raise ValueError(f"layout must be 'native' or 'hf', got {layout!r}")
    os.makedirs(out_dir, exist_ok=True)
    for fn in glob(f"{src_dir}/*"):
        base = os.path.basename(fn)
        if (
            os.path.isfile(fn)
            and ".bin" not in base
            and not base.endswith(".safetensors")
            and not base.endswith(".index.json")
        ):
            shutil.copy(fn, os.path.join(out_dir, base))

    weight_map, kind = _hf_weight_map(src_dir)

    remap, text_cfg = _multimodal_remap(src_dir)
    if remap is not None:
        # Extracting the text tower from a vision+text bundle: drop the
        # vision/projector keys, rename language-model keys to the plain
        # text layout, and emit the nested text_config as the config.
        renamed: dict[str, str] = {}
        for k in list(weight_map):
            nk = remap(k)
            if nk is None:
                del weight_map[k]
            elif nk != k:
                renamed[k] = nk
        weight_map = {renamed.get(k, k): v for k, v in weight_map.items()}
        with open(os.path.join(out_dir, "config.json"), "w") as f:
            json.dump(text_cfg, f, indent=1)
    layer2keys: dict[str, set[str]] = {}
    for k in weight_map:
        layer2keys.setdefault(key_to_layer(k), set()).add(k)
    layer2shards = {
        layer: {weight_map[k] for k in keys} for layer, keys in layer2keys.items()
    }
    # Reference ordering rule (/root/reference/prepare_weights.py:28).
    shard_ids = {s: i for i, s in enumerate(sorted({s for ss in layer2shards.values() for s in ss}))}
    layer_list = sorted(
        layer2shards,
        key=lambda l: (min(shard_ids[s] for s in layer2shards[l]), len(layer2shards[l])),
    )

    quantize = dtype in ("int8", "int4")
    if quantize and layout != "native":
        raise ValueError(f"dtype='{dtype}' requires layout='native'")
    if dtype == "bfloat16":
        if _BFLOAT16 is None:
            raise ImportError("dtype='bfloat16' requires ml_dtypes")
        cast = _BFLOAT16
    elif quantize:
        cast = None  # quantized below, after the native-layout conversion
    else:
        cast = np.dtype(dtype) if dtype is not None else None

    state: dict[str, np.ndarray] = {}
    loaded: set[str] = set()
    manifest_layers: dict[str, dict] = {}
    for layer in layer_list:
        for shard in layer2shards[layer] - loaded:
            loaded.add(shard)
            # Selective read: dropped (vision/projector) keys are skipped at
            # the safetensors layer, so a bundle's vision tower never
            # materialises in RAM.
            want = (
                (lambda k: remap(k) is not None) if remap is not None else None
            )
            for k, v in _load_shard(
                os.path.join(src_dir, shard), kind, want=want
            ).items():
                nk = remap(k) if remap is not None else k
                state[nk] = v
        missing = layer2keys[layer] - state.keys()
        if missing:
            raise KeyError(
                f"{layer}: keys {sorted(missing)} listed in the index but absent "
                f"from shards {sorted(layer2shards[layer])}"
            )
        sd = {k: state[k] for k in layer2keys[layer]}
        if cast is not None:
            sd = {
                k: np.asarray(v, dtype=cast) if is_float_like(v) else v
                for k, v in sd.items()
            }
        if layout == "native":
            sd = hf_layer_to_native(layer, sd)
        if quantize:
            sd = _quantize_flat(sd, dtype)
        stored = {k: np.ascontiguousarray(v) for k, v in sd.items()}
        st_save_file(stored, os.path.join(out_dir, f"{layer}{LAYER_FILE_SUFFIX}"))
        # Per-layer content checksums over the EXACT stored bytes — the
        # loader verifies every subsequent read against this manifest
        # (integrity/manifest.py; written atomically after the last layer).
        manifest_layers[layer] = integrity_manifest.layer_entry(
            stored, f"{layer}{LAYER_FILE_SUFFIX}"
        )
        del stored
        for k in layer2keys[layer]:
            del state[k]
        del sd
        gc.collect()
        if progress:
            progress(layer)

    with open(os.path.join(out_dir, NATIVE_LAYOUT_MARKER), "w") as f:
        json.dump({"layout": layout, "dtype": dtype, "layers": layer_list}, f)
    integrity_manifest.write_manifest(out_dir, manifest_layers)
    return layer_list


# ---------------------------------------------------------------------------
# Per-layer loading (the streaming hot path, host side)
# ---------------------------------------------------------------------------

# safetensors dtype tag -> numpy dtype (BF16 via ml_dtypes).
_ST_DTYPES: dict[str, Any] = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}
if _BFLOAT16 is not None:
    _ST_DTYPES["BF16"] = _BFLOAT16


def safetensors_header(path: str) -> tuple[dict[str, dict], int]:
    """Parse a safetensors file's header WITHOUT touching the payload:
    ``({key: {"dtype": tag, "shape": [...], "data_offsets": [b, e]}},
    payload_base_offset)``. One small read — byte-accounting estimators
    (``residency.layer_stream_bytes``) use it to see a layer's stored
    shapes/dtypes without faulting a multi-GB payload into RAM."""
    with open(path, "rb") as f:
        n = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(n))
    header.pop("__metadata__", None)
    return header, 8 + n


def _mmap_safetensors(path: str) -> dict[str, np.ndarray]:
    """True zero-copy safetensors read: parse the header, then return
    read-only ``np.memmap`` views into the payload.

    ``safetensors.numpy.load_file`` copies every tensor into a fresh buffer;
    on the streaming hot path that is a full extra pass over the model per
    stream (13.5 GB of memcpy for a 7B). A view costs nothing up front — the
    pages fault in from the page cache (kept warm by the native readahead
    pool) *during* the host->HBM ``device_put``, overlapping disk I/O with
    the transfer itself. Falls back to the library loader for any dtype tag
    this table doesn't know.
    """
    header, base = safetensors_header(path)
    if any(m["dtype"] not in _ST_DTYPES for m in header.values()):
        return st_load_file(path)
    mm = np.memmap(path, mode="r", dtype=np.uint8)
    out = {}
    for k, meta in header.items():
        b, e = meta["data_offsets"]
        dt = np.dtype(_ST_DTYPES[meta["dtype"]])
        if (
            b < 0
            or e < b
            or e - b != int(np.prod(meta["shape"])) * dt.itemsize
            or base + e > mm.size
        ):
            # Truncated/corrupt payload (e.g. a split killed mid-write):
            # the library loader raises the clear format error.
            return st_load_file(path)
        out[k] = mm[base + b : base + e].view(dt).reshape(meta["shape"])
    return out


def dequantize_tree_np(tree):
    """Host-side dequantize of every {"q8","s"} leaf-group in a pytree —
    for consumers that need real-valued host params (the streamed trainer;
    test oracles). The streaming executors dequantize ON DEVICE instead
    (runtime/executor._dequant_tree), after the int8 bytes cross the link."""
    import jax

    return jax.tree.map(
        lambda n: dequantize_np(n) if is_quantized_leaf(n) else n,
        tree,
        is_leaf=is_quantized_leaf,
    )


def load_layer(
    model_path: str,
    layer_name: str,
    manifest: dict | None = None,
    corrupt=None,
) -> dict[str, Any]:
    """Load one layer file into a native-layout parameter pytree (numpy;
    zero-copy mmap views where the file is already native layout). int8-
    compressed tensors come back as {"q8", "s"} leaf-groups, still int8 —
    dequantization happens on device, after the transfer.

    ``manifest``: an integrity manifest (integrity/manifest.py) — when it
    covers this layer, every stored tensor's checksum is verified and a
    mismatch raises the retryable ``ChecksumMismatch`` (re-reads heal
    page-cache corruption; the loader escalates persistence).
    ``corrupt``: chaos-only hook (``FaultInjector.corrupt_flat``) applied
    to the raw flat tensors BEFORE verification, so injected silent
    corruption is exactly what the checksums must catch."""
    path = os.path.join(model_path, f"{layer_name}{LAYER_FILE_SUFFIX}")
    # Verdict identity captured BEFORE the read, so a verify result can
    # only ever be recorded against the generation actually read.
    token = (
        integrity_manifest.verdict_token(model_path, path)
        if manifest is not None
        else None
    )
    flat = raw = _mmap_safetensors(path)
    # Re-stat AFTER the mmap: pre==post brackets the mapping, proving the
    # bytes belong to the generation the token names. On drift (the file
    # was atomically replaced mid-load) the cached verdict of the OLD
    # generation must not vouch for the NEW bytes — drop the token, which
    # forces a full verify of this load and records nothing.
    if token is not None and (
        integrity_manifest.verdict_token(model_path, path) != token
    ):
        token = None
    if corrupt is not None:
        flat = corrupt(flat)
    if manifest is not None:
        # Amortized hashing: a file generation is crc-verified ONCE, then
        # later sweeps reuse the cached clean verdict keyed by the file's
        # and the manifest's stat (any on-disk change invalidates). The
        # cache is bypassed whenever the chaos injector actually corrupted
        # this load (corrupt_flat returns a COPY then) — injected in-memory
        # corruption must be caught by a real checksum pass every time.
        injected = flat is not raw
        if injected or not integrity_manifest.verdict_cached(token):
            integrity_manifest.verify_flat(
                layer_name, flat, manifest, path=path
            )
            if not injected:
                integrity_manifest.record_verdict(token)
        # Per-layer PRECISION check, on every load (cheap — a key scan
        # plus header dtypes, independent of the crc verdict cache): the
        # file's actual storage-dtype kind must match what the manifest
        # declares for this layer. Catches a silently swapped file whose
        # precision disagrees with the mixed-precision plan the manifest
        # was written against — typed and structural, never retried.
        entry = manifest.get("layers", {}).get(layer_name) or {}
        want_kind = entry.get("dtype")
        if want_kind is not None:
            got_kind = flat_dtype_kind(flat)
            if got_kind != want_kind:
                raise integrity_manifest.PrecisionMismatch(
                    f"{path}: layer {layer_name!r} stores dtype kind "
                    f"{got_kind!r} but the integrity manifest declares "
                    f"{want_kind!r} — the file does not match the "
                    "precision the checkpoint was prepared at (audit "
                    "with the `verify` CLI subcommand)"
                )
    if not _is_native(flat.keys()):
        flat = hf_layer_to_native(layer_name, flat)
    if any(k.endswith((QUANT_SCALE_SUFFIX, QUANT4_SCALE_SUFFIX)) for k in flat):
        grouped: dict[str, Any] = {}
        for k, v in flat.items():
            if k.endswith((QUANT_SCALE_SUFFIX, QUANT4_SCALE_SUFFIX)):
                continue
            s8, s4 = k + QUANT_SCALE_SUFFIX, k + QUANT4_SCALE_SUFFIX
            if s4 in flat:
                grouped[k] = {"q4": v, "s": flat[s4]}
            elif s8 in flat:
                grouped[k] = {"q8": v, "s": flat[s8]}
            else:
                grouped[k] = v
        flat = grouped
    return native_to_pytree(layer_name, flat)


def _cast_flat_bf16(sd: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Cast every float tensor to bfloat16 — the SAME uniform cast rule
    ``split_into_layers(dtype='bfloat16')`` applies, so a plan's bf16
    layers are bit-identical to the uniform-bf16 baseline checkpoint."""
    if _BFLOAT16 is None:
        raise ImportError("dtype='bfloat16' requires ml_dtypes")
    return {
        k: np.asarray(v, dtype=_BFLOAT16) if is_float_like(v) else v
        for k, v in sd.items()
    }


def _encode_flat(sd: dict[str, np.ndarray], dtype: str) -> dict[str, np.ndarray]:
    """One layer's flat native tensors re-encoded at ``dtype`` — the
    per-layer primitive requantize_native applies uniformly or per a
    PrecisionPlan. Plan dtype 'bf16' aliases the storage name."""
    if dtype in ("bfloat16", "bf16"):
        return _cast_flat_bf16(sd)
    return _quantize_flat(sd, dtype)


def requantize_native(
    src_dir: str, out_dir: str, dtype: str = "int8", plan=None
) -> list[str]:
    """Re-encode an existing NATIVE per-layer checkpoint dir as int8
    (per-output-channel), int4 (group-wise packed), bfloat16 (cast only)
    — same conventions as ``split_into_layers(dtype=...)`` — or, with
    ``plan`` (a ``runtime.precisionplan.PrecisionPlan``), at a PER-LAYER
    dtype mix, without going back through the HF source. A plan must
    cover every layer file (a partial plan raises — silently defaulting
    a layer's precision is exactly the drift the plan artifact exists to
    prevent); the plan is embedded in the output dir
    (``precision_plan.json``) and the fresh integrity manifest records
    each layer's dtype kind, so the `verify` audit and the load path can
    both detect a plan/file mismatch as a typed error. Copies aux files
    (config.json, tokenizer) alongside. Returns the layer names
    converted."""
    if plan is None and dtype not in ("int8", "int4", "bfloat16"):
        raise ValueError(f"requantize_native: unsupported dtype {dtype!r}")
    if plan is not None:
        # Coverage validated BOTH ways BEFORE the first byte is written:
        # a drifted plan must fail up front, not strand a half-quantized
        # output dir (layer files but no manifest, no embedded plan —
        # which would later load unverified) after hours of work.
        on_disk = {
            fn[: -len(LAYER_FILE_SUFFIX)]
            for fn in os.listdir(src_dir)
            if fn.endswith(LAYER_FILE_SUFFIX)
        }
        missing = on_disk - set(plan.dtypes)
        extra = set(plan.dtypes) - on_disk
        if missing or extra:
            raise ValueError(
                f"precision plan and {src_dir} drifted: layers on disk "
                f"with no plan entry {sorted(missing)}; planned layers "
                f"with no file {sorted(extra)}"
            )
    os.makedirs(out_dir, exist_ok=True)
    # Function-level import (checkpoint is imported by precisionplan at
    # module scope; by requantize time both are importable).
    from flexible_llm_sharding_tpu.runtime.precisionplan import (
        PLAN_NAME as _PLAN_NAME,
    )

    done = []
    manifest_layers: dict[str, dict] = {}
    for fn in sorted(os.listdir(src_dir)):
        src = os.path.join(src_dir, fn)
        if not fn.endswith(LAYER_FILE_SUFFIX):
            # The source's integrity manifest must NOT ride along — its
            # checksums describe the float tensors, not the re-encoded
            # ones; a fresh manifest is written below. A source-embedded
            # precision plan is stale for the same reason.
            if (
                os.path.isfile(src)
                and fn != NATIVE_LAYOUT_MARKER
                and fn != integrity_manifest.MANIFEST_NAME
                and fn != _PLAN_NAME
            ):
                shutil.copy(src, os.path.join(out_dir, fn))
            continue
        layer_name = fn[: -len(LAYER_FILE_SUFFIX)]
        flat = _mmap_safetensors(src)
        if not _is_native(flat.keys()):
            raise ValueError(f"{fn}: not native layout (run split_into_layers)")
        if any(
            k.endswith((QUANT_SCALE_SUFFIX, QUANT4_SCALE_SUFFIX)) for k in flat
        ):
            # Re-quantizing a quantized dir would treat the 2-D fp32 scale
            # tensors as kernels (int4's ::scale4 in particular) and emit
            # silently-corrupt files; demand the original float checkpoint.
            raise ValueError(
                f"{fn}: source is already quantized; requantize from the "
                "original float checkpoint"
            )
        layer_dtype = dtype if plan is None else plan.dtype_for(layer_name)
        qd = _encode_flat(flat, layer_dtype)
        stored = {k: np.ascontiguousarray(v) for k, v in qd.items()}
        st_save_file(stored, os.path.join(out_dir, fn))
        manifest_layers[layer_name] = integrity_manifest.layer_entry(
            stored, fn
        )
        done.append(layer_name)
    if plan is not None:
        plan.save(out_dir)
    with open(os.path.join(out_dir, NATIVE_LAYOUT_MARKER), "w") as f:
        json.dump(
            {
                "layout": "native",
                "dtype": "mixed" if plan is not None else dtype,
                "layers": done,
            },
            f,
        )
    integrity_manifest.write_manifest(out_dir, manifest_layers)
    return done


def save_params(params: dict[str, Any], out_dir: str, cfg: LlamaConfig) -> None:
    """Save a full in-memory params pytree as per-layer native files + config.json
    (test/synthetic-model helper; the offline path is split_into_layers)."""
    os.makedirs(out_dir, exist_ok=True)

    def flatten(tree: dict[str, Any], prefix: str = "") -> Iterator[tuple[str, np.ndarray]]:
        for k, v in tree.items():
            name = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                yield from flatten(v, name)
            else:
                # np.asarray over a jax array can yield a non-contiguous view;
                # safetensors serializes the raw buffer ignoring strides, so
                # contiguity is mandatory here.
                yield name, np.ascontiguousarray(np.asarray(v))

    manifest_layers: dict[str, dict] = {}

    def _save(layer_name: str, tree: dict[str, Any]) -> None:
        flat = dict(flatten(tree))
        st_save_file(flat, os.path.join(out_dir, f"{layer_name}.safetensors"))
        manifest_layers[layer_name] = integrity_manifest.layer_entry(
            flat, f"{layer_name}.safetensors"
        )

    _save("model.embed_tokens", params["embed"])
    for i, layer in enumerate(params["layers"]):
        _save(f"model.layers.{i}", layer)
    _save("model.norm", params["norm"])
    if "lm_head" in params and params["lm_head"]:
        _save("lm_head", params["lm_head"])
    integrity_manifest.write_manifest(out_dir, manifest_layers)
    import dataclasses as _dc

    # EVERY dataclass field serializes by name (tuples become json lists;
    # from_hf_config's native path coerces the known tuple fields back).
    # A hand-maintained field list here silently dropped newly-added fields
    # (an MLA config round-tripped to the 128/64 head-dim defaults) — the
    # asdict dump cannot drift.
    hf_cfg = {
        # Marks a config this framework wrote itself: every native field is
        # explicit and from_hf_config round-trips them all by name. Foreign
        # configs (no marker) get the per-family stray-key defence instead.
        "fls_native": True,
        "use_sliding_window": cfg.sliding_window is not None,  # qwen2 gate
        **_dc.asdict(cfg),
    }
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f)
