"""Host-side utilities: checkpoint preparation/loading, tokenisation, prompt I/O."""
