"""Observability: structured metrics, HBM stats, profiler hooks.

The reference's observability is a wall-clock counter around the weight load
printed at the end (``/root/reference/utils.py:223,230-233,304``) plus tqdm
bars. Here (SURVEY.md §5): the same load-time counter, plus per-shard
structured events, tokens/sec/chip, peak HBM from the runtime's allocator
stats, and a ``jax.profiler`` trace context for Perfetto/XProf dumps.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time
from dataclasses import dataclass, field

from flexible_llm_sharding_tpu.obs import events as obs_events
from flexible_llm_sharding_tpu.obs import trace as obs_trace


def device_memory_stats(device=None) -> dict[str, float]:
    """Allocator stats for one chip (bytes). Empty on backends without
    memory_stats (CPU)."""
    import jax

    # local_devices, not devices: on a multi-host cluster jax.devices()[0]
    # is process 0's chip, and MemoryStats on a non-addressable device
    # raises on every other rank.
    device = device or jax.local_devices()[0]
    try:
        stats = getattr(device, "memory_stats", lambda: None)()
    except Exception:  # tunnel-backed devices can also refuse the query
        return {}
    if not stats:
        return {}
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if key in stats:
            out[key] = float(stats[key])
    return out


def peak_hbm_gb(device=None) -> float | None:
    s = device_memory_stats(device)
    return s["peak_bytes_in_use"] / 1e9 if "peak_bytes_in_use" in s else None


def _host_rss_bytes() -> dict[str, int]:
    """``{"peak": VmHWM, "anon": RssAnon}`` in bytes from
    ``/proc/self/status``; empty off-Linux. Early-exits once both keys are
    parsed (RssAnon follows VmHWM) — this runs on every sampler tick."""
    out: dict[str, int] = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    out["peak"] = int(line.split()[1]) * 1024
                elif line.startswith("RssAnon:"):
                    out["anon"] = int(line.split()[1]) * 1024
                    break
    except OSError:
        pass
    return out


def host_rss_gb() -> dict[str, float]:
    """Host memory (GB): ``peak`` (VmHWM — peak RSS, which INCLUDES
    file-backed pages the mmap checkpoint loader faulted in, so on an
    unpressured host it can approach the full model size) and ``anon``
    (RssAnon — the process's own private buffers, the number that witnesses
    the streaming design's host-memory bound). Empty off-Linux."""
    return {k: v / 1e9 for k, v in _host_rss_bytes().items()}


class LiveArrayPeakSampler:
    """Peak device-resident bytes, sampled from ``jax.live_arrays()``.

    Fallback evidence for platforms whose devices report no allocator stats
    (``memory_stats() is None`` — e.g. TPU behind the axon tunnel): a daemon
    thread samples the total bytes of live JAX arrays on the default backend.
    This counts weights, activations, and queued prefetch shards — everything
    the framework holds — but NOT XLA's internal scratch inside a running
    executable; pair it with ``compiled_memory_analysis`` for that side.
    Use as a context manager; read ``.peak_gb`` after exit.
    """

    def __init__(self, interval_s: float = 0.05):
        self.interval_s = interval_s
        self.peak_bytes = 0
        # Peak ANON host RSS sampled alongside: VmHWM counts mmapped
        # checkpoint pages, RssAnon is the process's own buffers — but
        # RssAnon has no kernel-tracked high-water mark, so sample it.
        self.peak_anon_bytes = 0
        self._stop = None
        self._thread = None

    def _sample(self) -> None:
        import jax
        import numpy as np

        def device_bytes(a) -> int:
            # Actual per-device buffer bytes, from sharding METADATA only: a
            # replicated array's .nbytes is its logical global size (which
            # would undercount tp-replication), and touching .data would
            # materialize view arrays that the next sample then counts.
            # Donated/deleted arrays hold no HBM.
            try:
                if a.is_deleted():
                    return 0
                sh = a.sharding
                shard_elems = int(np.prod(sh.shard_shape(a.shape)))
                return shard_elems * a.dtype.itemsize * len(sh.addressable_devices)
            except Exception:
                return a.nbytes

        # Host sample first: it has no JAX dependency and must not be
        # skipped when live-array enumeration fails (backend not up yet,
        # tunnel hiccup).
        anon = _host_rss_bytes().get("anon")
        if anon is not None:
            self.peak_anon_bytes = max(self.peak_anon_bytes, anon)
        try:
            total = sum(device_bytes(a) for a in jax.live_arrays())
        except Exception:
            return
        if total > self.peak_bytes:
            self.peak_bytes = total

    def _run(self) -> None:
        while not self._stop.is_set():
            self._sample()
            self._stop.wait(self.interval_s)

    def __enter__(self) -> "LiveArrayPeakSampler":
        import threading

        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._sample()

    @property
    def peak_gb(self) -> float:
        return self.peak_bytes / 1e9


def compiled_memory_analysis(jitted, *args, **kwargs) -> dict[str, float]:
    """XLA's own memory accounting for one jitted function at given shapes:
    argument/output/temp/generated-code bytes. The temp figure is the scratch
    a ``LiveArrayPeakSampler`` cannot see; argument+temp+output bounds the
    executable's true HBM footprint."""
    lowered = jitted.lower(*args, **kwargs)
    mem = lowered.compile().memory_analysis()
    if mem is None:
        return {}
    out = {}
    for key in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        val = getattr(mem, key, None)
        if val is not None:
            out[key] = float(val)
    return out


@dataclass
class Recorder:
    """Append-only structured event log for one run.

    Events are (name, seconds, extra) tuples; ``summary()`` aggregates by
    name. ``emit()`` writes one JSON line per event to stderr when verbose.
    """

    verbose: bool = False
    events: list[tuple[str, float, dict]] = field(default_factory=list)

    def record(self, name: str, seconds: float, **extra) -> None:
        self.events.append((name, seconds, extra))
        if self.verbose:
            print(
                json.dumps({"event": name, "seconds": round(seconds, 4), **extra}),
                file=sys.stderr,
                flush=True,
            )

    @contextlib.contextmanager
    def timed(self, name: str, **extra):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0, **extra)

    def total(self, name: str) -> float:
        return sum(s for n, s, _ in self.events if n == name)

    def summary(self) -> dict[str, dict[str, float]]:
        agg: dict[str, dict[str, float]] = {}
        for name, s, _ in self.events:
            d = agg.setdefault(name, {"count": 0.0, "seconds": 0.0})
            d["count"] += 1
            d["seconds"] += s
        return agg


def _latency_summary(samples: list[float]) -> dict[str, float]:
    """{count, mean, p50, p95, p99, max} in seconds for a latency sample
    list (the quantile set the Prometheus exposition and the trace
    analyzer share)."""
    if not samples:
        return {"count": 0}
    import numpy as np

    arr = np.asarray(samples, np.float64)
    return {
        "count": int(arr.size),
        "mean": round(float(arr.mean()), 4),
        "p50": round(float(np.percentile(arr, 50)), 4),
        "p95": round(float(np.percentile(arr, 95)), 4),
        "p99": round(float(np.percentile(arr, 99)), 4),
        "max": round(float(arr.max()), 4),
    }


class RetryRecorder:
    """Thread-safe transient-I/O retry accounting (fed by faults/retry.py's
    ``retry_call``). Keyed by call-site label (``shard_read``,
    ``device_put``, ...); per label: ``retries`` (backoff sleeps taken),
    ``recovered`` (calls that succeeded after >= 1 retry), ``exhausted``
    (calls that gave up — the typed ShardLoadError path), ``backoff_s``
    (total sleep). One recorder per executor/engine, so runs don't bleed
    into each other's counts."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._by_label: dict[str, dict[str, float]] = {}

    def record(
        self,
        label: str,
        *,
        retries: int = 0,
        recovered: int = 0,
        exhausted: int = 0,
        backoff_s: float = 0.0,
    ) -> None:
        with self._lock:
            d = self._by_label.setdefault(
                label or "call",
                {"retries": 0, "recovered": 0, "exhausted": 0, "backoff_s": 0.0},
            )
            d["retries"] += retries
            d["recovered"] += recovered
            d["exhausted"] += exhausted
            d["backoff_s"] += backoff_s

    def total(self, key: str = "retries") -> float:
        with self._lock:
            return sum(d[key] for d in self._by_label.values())

    def snapshot(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {
                k: {
                    kk: round(vv, 4) if kk == "backoff_s" else int(vv)
                    for kk, vv in d.items()
                }
                for k, d in sorted(self._by_label.items())
            }


class IntegrityRecorder:
    """Thread-safe corruption-accounting counters (fed by the integrity
    layer: ``_HostShardLoader``, ``ActivationStore``, the executor's
    recompute path). Keys: ``integrity_failures`` (checksum mismatches /
    unreadable spills DETECTED), ``reread_heals`` (loads that came back
    clean on a re-read — page-cache/NFS corruption healed in place),
    ``recomputes`` (blocks re-derived from the last good shard boundary
    after a persistent spill mismatch), ``quarantined_shards`` (weight
    files whose corruption survived every re-read). Surfaced in executor
    stats and the serve stats line when nonzero."""

    KEYS = (
        "integrity_failures",
        "reread_heals",
        "recomputes",
        "quarantined_shards",
    )

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._counts: dict[str, int] = {k: 0 for k in self.KEYS}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def total(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)


# SLO class names for the per-class latency breakdown — mirrored from
# serve/sched/classes.py (importing it here would cycle: engine ->
# metrics -> serve). tests/test_sched.py pins the two tuples in sync.
# Pre-seeded so the fls_serve_ttft_by_class_* / latency_by_class_*
# families are always scrapeable ("no samples yet" vs "not exported").
SLO_CLASS_NAMES = ("interactive", "standard", "best_effort")


# The stats-line / exposition merge policy for the serve registry's
# WELL-KNOWN source names: these get the layout operators and CI greps
# already depend on (nested-when-nonzero, top-level convenience keys);
# any OTHER registered source appears as its own nested dict when it
# carries a nonzero value. This is the ONE assembly path — the engine's
# stats() and ServingMetrics.snapshot() both go through it, so the line
# can never fork again.
_SERVE_CORE_SOURCES = (
    "serve", "io_retries", "integrity", "host_cache", "residency",
)


def assemble_serve_stats(collected: dict) -> dict:
    """One serve stats line from a registry collection (see
    ``ServingMetrics.snapshot``)."""
    out: dict = {"event": "serve_stats"}
    out.update(collected.get("serve", {}))
    retries = collected.get("io_retries")
    if retries:
        out["io_retries"] = retries
    integrity = collected.get("integrity")
    if integrity and any(integrity.values()):
        out["integrity"] = integrity
    # .get(), never []: a failing source degrades to {"collect_error": 1}
    # in the collection (obs/registry.py) — the stats line must render
    # around it, not turn the tolerated failure into a KeyError that the
    # serve loop's fatal path would promote to killing the engine.
    cache = collected.get("host_cache")
    if cache is not None:
        if "hit_rate" in cache:
            out["host_cache_hit_rate"] = cache["hit_rate"]
        out["host_cache"] = cache
    res = collected.get("residency")
    if res is not None:
        if "pinned_bytes" in res:
            out["pinned_bytes"] = res["pinned_bytes"]
        if "stream_bytes_saved" in res:
            out["stream_bytes_saved"] = res["stream_bytes_saved"]
        out["residency"] = res
    for name in sorted(collected):
        if name in _SERVE_CORE_SOURCES:
            continue
        snap = collected[name]
        if any(isinstance(v, (int, float)) and v for v in snap.values()):
            out[name] = snap
    return out


class ServingMetrics:
    """Counters/gauges/latency samples for the online serving subsystem.

    Thread-safe (submitters, the serving loop, and callbacks all touch it).
    Counters: admitted / rejected / expired / cancelled / completed /
    failed / prefills / sweeps / tokens_emitted (pre-seeded to 0 so the
    Prometheus exposition always carries the full family — a scrape can
    tell "zero recoveries" from "recoveries not exported"). Gauges:
    queue_depth / active_requests / active_waves. Latency samples: ttft_s
    (submit -> first token) and token_s (per-token decode latency) — kept
    in a BOUNDED window (``sample_window`` newest samples) so a
    long-running server neither grows memory with uptime nor recomputes
    percentiles over its whole history inside the lock; the summaries are
    therefore recent-window statistics, while the counters remain
    all-time totals.

    Every part registers into ``self.registry`` (an
    ``obs.registry.MetricsRegistry``): its own counters/gauges/latency
    under ``serve``, the retry and integrity recorders, and whatever the
    engine attaches (host cache, residency tier, watchdog, tracer, the
    process stream counters). ``snapshot()`` — the periodic structured
    stats line — and the engine's Prometheus endpoint both render from
    that one registry, so the two can never drift. The same sources are
    mirrored into the process-wide registry (last engine wins, the
    process cache/tier precedent) for the batch-style one-shot dump.
    ``maybe_emit(interval)`` prints the line to stderr at most once per
    interval (0 disables)."""

    KNOWN_COUNTERS = (
        "admitted",
        "rejected",
        "expired",
        "cancelled",
        "completed",
        "failed",
        "prefills",
        # Prefix-prefill token accounting (runtime/kvpool.py reuse):
        # prefix_prefill_tokens = prefix tokens actually prefilled;
        # prefix_reuse_tokens = prefix tokens served from pooled pages
        # with ZERO prefill recompute (the kv_prefix_reuse_frac bench
        # metric is reuse / (reuse + prefill)).
        "prefix_prefill_tokens",
        "prefix_reuse_tokens",
        "sweeps",
        "tokens_emitted",
        "engine_recoveries",
        "waves_aborted",
        "source_restarts",
        "watchdog_stalls",
    )

    def __init__(
        self, sample_window: int = 4096, process_mirror: bool = True
    ) -> None:
        import threading
        from collections import deque

        from flexible_llm_sharding_tpu.obs.registry import MetricsRegistry

        # process_mirror=False (fleet-owned engines): keep every source in
        # this engine's OWN registry but never mirror it process-wide —
        # with N replicas the last-wins 'serve'/'io_retries'/... names
        # would otherwise expose ONE arbitrary replica's counters as the
        # process family (and drop the family entirely whenever that
        # replica is recycled). The fleet exports per-replica mirrors
        # under replica<idx> instead.
        self.process_mirror = process_mirror
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {k: 0 for k in self.KNOWN_COUNTERS}
        self._gauges: dict[str, float] = {}
        self._ttft: deque[float] = deque(maxlen=sample_window)
        self._token_lat: deque[float] = deque(maxlen=sample_window)
        # Per-SLO-class breakdowns (serve/sched): TTFT and full request
        # latency, same bounded-window semantics as the aggregate above.
        self._ttft_class: dict[str, deque] = {
            c: deque(maxlen=sample_window) for c in SLO_CLASS_NAMES
        }
        self._latency_class: dict[str, deque] = {
            c: deque(maxlen=sample_window) for c in SLO_CLASS_NAMES
        }
        self._last_emit = 0.0
        # Transient-I/O retry accounting for this engine's weight stream
        # (the engine threads it into its sources' loaders).
        self.retries = RetryRecorder()
        # Corruption accounting (checksum failures / re-read heals /
        # quarantines) for the same stream — nonzero counters appear in
        # the stats line under "integrity".
        self.integrity = IntegrityRecorder()
        # Speculative-serving draft economy (serve/engine.py spec path):
        # pre-seeded so the fls_spec_* family is always scrapeable —
        # "zero drafts" vs "spec not exported" — and registered as its
        # OWN source so the exposition names are fls_spec_drafted_tokens
        # / fls_spec_accepted_tokens / fls_spec_rejected_tokens plus the
        # derived acceptance_rate and extra_tokens_per_sweep.
        self._spec: dict[str, int] = {
            "drafted_tokens": 0,
            "accepted_tokens": 0,
            "rejected_tokens": 0,
        }
        # Per-SLO-class split of the same family (pre-seeded zeros for
        # every class so fls_spec_by_class_<class>_<counter> is always
        # scrapeable) — the adaptive controller's input signal
        # (serve/spec.py) must be observable from the outside too.
        self._spec_class: dict[str, dict[str, int]] = {
            c: {
                "drafted_tokens": 0,
                "accepted_tokens": 0,
                "rejected_tokens": 0,
            }
            for c in SLO_CLASS_NAMES
        }
        self.registry = MetricsRegistry()
        self._host_cache = None
        self._residency = None
        # Mirrored names -> the exact source object registered process-
        # wide, so close() can retract THIS engine's mirrors without
        # yanking a newer engine's (unregister_if identity check).
        self._mirrored: dict[str, object] = {}
        self.register("serve", self._core_snapshot)
        self.register("io_retries", self.retries.snapshot)
        self.register("integrity", self.integrity.snapshot)
        self.register("spec", self.spec_snapshot)

    def register(self, name: str, source, mirror: bool = True) -> None:
        """Register a source into this engine's registry and (for
        engine-scoped sources) mirror it into the process-wide one — last
        engine wins there, and ``close()`` retracts the mirrors so a dead
        engine neither serves stale counters nor pins its object graph.
        Pass ``mirror=False`` for PROCESS-level sources (the stream
        counters, the tracer, the host cache, the residency tier): their
        owners register them process-wide themselves, and an engine
        mirror would tear them down with the engine."""
        from flexible_llm_sharding_tpu.obs.registry import REGISTRY

        self.registry.register(name, source)
        if mirror and self.process_mirror:
            self._mirrored[name] = source
            REGISTRY.register(name, source)

    def close(self) -> None:
        """Retract this engine's process-wide mirrors (engine shutdown).
        Idempotent; a newer engine's same-name registrations survive."""
        from flexible_llm_sharding_tpu.obs.registry import REGISTRY

        for name, source in self._mirrored.items():
            REGISTRY.unregister_if(name, source)
        self._mirrored = {}

    # Host shard cache / residency tier attached by the serving engine —
    # kept as attribute-style setters for the existing call sites, but the
    # attach IS a registry registration: the stats line and the endpoint
    # read the same source. No process-wide mirror: both objects are
    # process-level and register themselves there (cache_for / tier_for),
    # so an engine detach must not disturb the live process source.
    @property
    def host_cache(self):
        return self._host_cache

    @host_cache.setter
    def host_cache(self, cache) -> None:
        self._host_cache = cache
        if cache is not None:
            self.register("host_cache", cache.stats, mirror=False)
        else:
            self.registry.unregister("host_cache")

    @property
    def residency(self):
        return self._residency

    @residency.setter
    def residency(self, tier) -> None:
        self._residency = tier
        if tier is not None:
            self.register("residency", tier.stats, mirror=False)
        else:
            self.registry.unregister("residency")

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe_ttft(self, seconds: float, slo_class: str | None = None) -> None:
        from collections import deque

        with self._lock:
            self._ttft.append(seconds)
            if slo_class is not None:
                self._ttft_class.setdefault(
                    slo_class, deque(maxlen=self._ttft.maxlen)
                ).append(seconds)

    def observe_request_latency(
        self, seconds: float, slo_class: str | None = None
    ) -> None:
        """Full submit->completion latency, bucketed per SLO class — the
        per-class half of the latency story (TTFT above is the other)."""
        if slo_class is None:
            return
        from collections import deque

        with self._lock:
            self._latency_class.setdefault(
                slo_class, deque(maxlen=self._ttft.maxlen)
            ).append(seconds)

    def observe_token_latency(self, seconds: float) -> None:
        with self._lock:
            self._token_lat.append(seconds)

    def ttft_class_samples(self, slo_class: str) -> list[float]:
        """Copy of one class's bounded TTFT window (obs/slo.py reads it
        at scrape time — pull-based, nothing on the serving hot path)."""
        with self._lock:
            d = self._ttft_class.get(slo_class)
            return list(d) if d is not None else []

    def token_latency_samples(self) -> list[float]:
        """Copy of the bounded per-token latency window (obs/slo.py)."""
        with self._lock:
            return list(self._token_lat)

    def spec_count(
        self, drafted: int = 0, accepted: int = 0, rejected: int = 0,
        slo_class: str | None = None,
    ) -> None:
        """One verify pass's draft economy (serve/engine.py spec path):
        USEFUL drafted slots, accepted, rejected — drafted == accepted +
        rejected by construction (SpecVerifier.finish_pass). With
        ``slo_class`` the same delta also lands in that class's split
        (the aggregate family stays the cross-class total either way)."""
        with self._lock:
            self._spec["drafted_tokens"] += drafted
            self._spec["accepted_tokens"] += accepted
            self._spec["rejected_tokens"] += rejected
            if slo_class is not None:
                cls = self._spec_class.setdefault(
                    slo_class,
                    {
                        "drafted_tokens": 0,
                        "accepted_tokens": 0,
                        "rejected_tokens": 0,
                    },
                )
                cls["drafted_tokens"] += drafted
                cls["accepted_tokens"] += accepted
                cls["rejected_tokens"] += rejected

    def spec_snapshot(self) -> dict:
        """The ``spec`` registry source: raw counters + the two derived
        headline figures — acceptance rate (accepted / drafted) and extra
        tokens per sweep (accepted / sweeps: how many tokens beyond the
        baseline one-per-sweep each weight sweep bought)."""
        with self._lock:
            drafted = self._spec["drafted_tokens"]
            accepted = self._spec["accepted_tokens"]
            sweeps = self._counters.get("sweeps", 0)
            return {
                **self._spec,
                "acceptance_rate": round(accepted / drafted, 4)
                if drafted
                else 0.0,
                "extra_tokens_per_sweep": round(accepted / sweeps, 4)
                if sweeps
                else 0.0,
                "by_class": {
                    c: dict(v) for c, v in sorted(self._spec_class.items())
                },
            }

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def _core_snapshot(self) -> dict:
        """The engine's own counters/gauges/latency summaries — the
        ``serve`` registry source."""
        with self._lock:
            return {
                **{k: v for k, v in sorted(self._counters.items())},
                **{k: v for k, v in sorted(self._gauges.items())},
                "ttft_s": _latency_summary(list(self._ttft)),
                "token_latency_s": _latency_summary(list(self._token_lat)),
                # Per-SLO-class breakdowns (serve/sched): always present
                # (classes pre-seeded) so the fls_serve_*_by_class_*
                # families are scrapeable even before the first sample.
                "ttft_by_class": {
                    c: _latency_summary(list(d))
                    for c, d in sorted(self._ttft_class.items())
                },
                "latency_by_class": {
                    c: _latency_summary(list(d))
                    for c, d in sorted(self._latency_class.items())
                },
            }

    def snapshot(self) -> dict:
        return assemble_serve_stats(self.registry.collect())

    def emit(self) -> None:
        print(json.dumps(self.snapshot()), file=sys.stderr, flush=True)

    def maybe_emit(self, interval_s: float) -> bool:
        """Emit the stats line if ``interval_s`` has passed since the last
        emission (0 = off). Returns whether a line was printed."""
        if not interval_s:
            return False
        now = time.monotonic()
        with self._lock:
            if now - self._last_emit < interval_s:
                return False
            self._last_emit = now
        self.emit()
        return True


class RouterMetrics:
    """Counters/gauges for the replica fleet's router (``serve/fleet.py``).

    Thread-safe (submitter threads dispatch, engine threads report
    terminal outcomes, the health monitor drains/recycles). Counters are
    PRE-SEEDED to 0 (``KNOWN_COUNTERS``) so the Prometheus exposition
    always carries the full ``fls_router_*`` family — a scrape can tell
    "zero re-dispatches happened" from "re-dispatches not exported", the
    same zero-vs-unexported contract ``ServingMetrics.KNOWN_COUNTERS``
    established. The fleet registers ``snapshot`` into the process-wide
    metrics registry under the ``router`` source name."""

    KNOWN_COUNTERS = (
        "dispatches",          # requests handed to a replica (first attempt)
        "redispatches",        # orphans re-dispatched to a surviving replica
        "expired_orphans",     # orphans whose deadline lapsed -> EXPIRED
        "stale_results",       # outcomes from attempts the fleet abandoned
        "replicas_dead",       # hard-fails (engine-fatal / stalled watermark)
        "replicas_drained",    # graceful drains completed
        "replicas_recycled",   # fresh engines brought up in a dead/drained slot
        "replicas_added",      # elastic joins
        "replicas_removed",    # elastic leaves
    )

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._counters: dict[str, int] = {k: 0 for k in self.KNOWN_COUNTERS}
        self._gauges: dict[str, float] = {}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                **{k: v for k, v in sorted(self._counters.items())},
                **{k: v for k, v in sorted(self._gauges.items())},
            }


@contextlib.contextmanager
def profiler_trace(log_dir: str | None):
    """``jax.profiler`` trace scope (Perfetto/XProf) when a directory is
    given; no-op otherwise. View with ``xprof`` or perfetto.dev."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


class _NullBar:
    def update(self, n: int = 1) -> None:
        pass

    def set_postfix_str(self, s: str) -> None:
        pass

    def close(self) -> None:
        pass


class _WatchdogBar:
    """Wraps a progress bar with a stall watchdog: if no update lands for
    ``stall_warn_s`` a warning goes to stderr (repeated each further
    interval). A wedged accelerator tunnel otherwise means tens of minutes
    of silence in headless runs — the warning names the stalled loop and
    how long it has been stuck, which is the whole diagnosis."""

    def __init__(self, bar, desc: str, stall_warn_s: float):
        import threading

        self._bar = bar
        self._desc = desc
        self._interval = stall_warn_s
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._watch, daemon=True)
        self._thread.start()

    def _watch(self) -> None:
        warned = 0
        while not self._stop.wait(min(self._interval / 4, 30.0)):
            idle = time.monotonic() - self._last
            if idle >= self._interval * (warned + 1):
                warned += 1
                msg = (
                    f"[stall] '{self._desc}' has made no progress for "
                    f"{idle / 60:.1f} min — accelerator transfer/compute "
                    "may be wedged (tunnel flake?); the run will continue "
                    "if it unwedges, or can be killed and resumed "
                    "(--resume, disk mode)"
                )
                # tqdm.write, not print: a raw print from this thread would
                # splice into the bar's in-place-refreshed TTY line.
                writer = getattr(type(self._bar), "write", None)
                if callable(writer):
                    type(self._bar).write(msg, file=sys.stderr)
                else:
                    print(msg, file=sys.stderr, flush=True)
            elif idle < self._interval:
                warned = 0

    def update(self, n: int = 1) -> None:
        self._last = time.monotonic()
        self._bar.update(n)

    def set_postfix_str(self, s: str) -> None:
        self._bar.set_postfix_str(s)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._bar.close()


class StepWatchdog:
    """Step-progress watchdog with an ABORT action — ``_WatchdogBar``'s
    stall detection generalized from warn-only to recovery.

    ``arm(token)`` before a monitored phase, ``tick()`` on every unit of
    progress, ``disarm()`` when the phase completes. If an armed phase
    goes ``abort_s`` with no tick, ``on_stall(idle_s, token)`` fires ONCE
    from the watchdog thread and the phase self-disarms (the owner re-arms
    on its next phase). ``token`` identifies WHAT the armed period guards
    (the serving engine passes its current weight source): the callback is
    handed the token its own armed period captured, so a callback delayed
    across a recovery cannot be tricked into aborting the healthy
    replacement by re-reading mutable owner state at fire time.
    ``on_stall`` runs on the watchdog thread: it must be non-blocking
    (set a flag, close a queue), never join the stalled work itself."""

    def __init__(self, desc: str, abort_s: float, on_stall, poll_s=None):
        import threading

        if abort_s <= 0:
            raise ValueError("abort_s must be > 0")
        self._desc = desc
        self._abort_s = abort_s
        self._on_stall = on_stall
        self._poll_s = poll_s if poll_s is not None else max(abort_s / 4, 0.01)
        self._armed = False
        self._token = None
        self._last = time.monotonic()
        self.stalls = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._watch, daemon=True)
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.wait(self._poll_s):
            if not self._armed:
                continue
            idle = time.monotonic() - self._last
            if idle < self._abort_s:
                continue
            # Capture the armed period's token BEFORE anything that can
            # block (the print below can): the callback must act on what
            # stalled, not on whatever the owner armed next.
            token = self._token
            self._armed = False
            self.stalls += 1
            # Structured span event FIRST (non-blocking ring append): the
            # stall must be visible in the trace timeline — correlated
            # with the sweep it killed — not only as an exception text.
            obs_trace.instant(
                "watchdog_stall",
                cat="serve",
                desc=self._desc,
                idle_s=round(idle, 3),
                stalls=self.stalls,
            )
            # Durable twin of the trace instant: the stall that killed a
            # sweep must survive the recovery (or the process) it causes.
            obs_events.emit(
                "watchdog_stall",
                desc=self._desc,
                idle_s=round(idle, 3),
                stalls=self.stalls,
            )
            print(
                f"[stall] '{self._desc}' made no progress for {idle:.1f}s "
                "— aborting for recovery",
                file=sys.stderr,
                flush=True,
            )
            try:
                self._on_stall(idle, token)
            except Exception:
                pass  # recovery is best-effort; the watchdog must survive

    def stats(self) -> dict[str, int]:
        """Registry source: stall-abort count for the metrics endpoint."""
        return {"stalls": self.stalls}

    def arm(self, token=None) -> None:
        self._token = token
        self._last = time.monotonic()
        self._armed = True

    def tick(self) -> None:
        self._last = time.monotonic()

    def disarm(self) -> None:
        self._armed = False

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def progress_bar(total: int, desc: str, unit: str = "it", disable=None,
                 stall_warn_s: float = 600.0):
    """A tqdm bar over the streaming loops (the reference shows tqdm over the
    longer of its shard/prompt loops, ``/root/reference/utils.py:226-227,
    236-238``). ``disable=None`` = tqdm's auto mode: visible on a TTY, silent
    in CI/pipes. Falls back to a no-op if tqdm is missing. A stall watchdog
    warns on stderr when no update lands for ``stall_warn_s`` (0 disables)."""
    try:
        from tqdm import tqdm
    except ImportError:
        bar = _NullBar()
    else:
        bar = tqdm(total=total, desc=desc, unit=unit, disable=disable,
                   file=sys.stderr)
    if stall_warn_s and total > 0:
        return _WatchdogBar(bar, desc, stall_warn_s)
    return bar


def _arch_walk(cfg):
    """Shared per-layer structure walk for the analytic model-size helpers:
    (attn projection params, per-layer moe flags, dense MLP intermediate).
    ``model_flops_per_token`` and ``param_count`` both consume this so a new
    model-family field (moe pattern, shared expert, …) is resolved in ONE
    place — they differ only in counting ACTIVE vs ALL experts."""
    h = cfg.hidden_size
    hd = cfg.head_dim
    q_dim = cfg.num_attention_heads * hd
    kv_dim = cfg.num_key_value_heads * hd
    if cfg.kv_lora_rank:
        # MLA (deepseek): LoRA'd q (or dense wq), compressed kv_a, per-head
        # kv_b decompression, wo over the heads' v_head_dim outputs.
        q_p = (
            h * cfg.q_lora_rank + cfg.q_lora_rank * q_dim
            if cfg.q_lora_rank
            else h * q_dim
        )
        kv_p = h * (cfg.kv_lora_rank + cfg.qk_rope_head_dim) + (
            cfg.kv_lora_rank
            * cfg.num_attention_heads
            * (cfg.qk_nope_head_dim + cfg.v_dim)
        )
        attn_proj = q_p + kv_p + cfg.num_attention_heads * cfg.v_dim * h
    else:
        attn_proj = h * q_dim + 2 * h * kv_dim + q_dim * h
    n = cfg.num_hidden_layers
    moe_pattern = cfg.moe_layer_pattern or (
        ((True,) * n) if cfg.num_local_experts else ((False,) * n)
    )
    dense_inter = (
        cfg.intermediate_size_mlp
        if cfg.intermediate_size_mlp is not None
        else cfg.intermediate_size
    )
    return attn_proj, moe_pattern, dense_inter


def _shared_expert_mult(cfg) -> int:
    """Width of the always-on shared expert in units of the routed expert
    width: 0 (no shared expert), 1 (llama4), or ``cfg.n_shared_experts``
    (deepseek — ONE fused MLP of n_shared x the routed width, V2 uses 2)."""
    if cfg.model_type == "llama4_text":
        return 1
    if cfg.model_type == "deepseek_v3":
        # Parse already normalized (explicit 0 preserved, absent -> 1);
        # getattr only tolerates duck-typed test configs.
        return int(getattr(cfg, "n_shared_experts", 1))
    return 0


def model_flops_per_token(cfg, context_len: int = 0) -> float:
    """Analytic forward FLOPs per processed token for a LlamaConfig.

    2 FLOPs per matmul MAC over every parameter that participates in a
    matmul (projections, MLP, lm_head — embeddings are a gather, not FLOPs),
    plus the attention score/value terms (2*ctx*(qk head_dim + v_dim) per
    query head per token at mean context ``context_len`` — the dims differ
    under MLA, equal everywhere else). MoE layers count only the
    ACTIVE experts per token (top-k, + llama4's shared expert) plus the
    router. This is the numerator of MFU — the standard "model FLOPs"
    convention (no recompute, no masking discounts).
    """
    h = cfg.hidden_size
    attn_proj, moe_pattern, dense_inter = _arch_walk(cfg)
    # QK uses the (qk) head_dim, PV uses V's own dim (MLA: 192 vs 128).
    attn_scores = (
        context_len * (cfg.head_dim + cfg.v_dim) * cfg.num_attention_heads
    )

    total = 0.0
    for is_moe in moe_pattern:
        if is_moe:
            # Always-on shared expert: width 1x for llama4, n_shared_experts x
            # the routed width for deepseek (V2 checkpoints use 2).
            active = cfg.num_experts_per_tok + _shared_expert_mult(cfg)
            mlp = active * 3 * h * cfg.intermediate_size + h * cfg.num_local_experts
        else:
            mlp = 3 * h * dense_inter
        total += 2 * (attn_proj + mlp) + 2 * attn_scores
    total += 2 * h * cfg.vocab_size  # lm_head
    return float(total)


# Peak dense bf16 FLOP/s per chip, by device_kind substring (public TPU
# specs; the MFU denominator).
_PEAK_BF16_FLOPS = (
    ("v6", 918e12),  # Trillium / v6e
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
)


def measure_host_to_hbm_gbps(device=None, mb: int = 256) -> float:
    """Effective host->device transfer bandwidth (GB/s): one timed
    ``device_put`` of an ``mb``-MB buffer, after a SAME-SHAPE warm transfer
    so backend init, first-transfer setup, and the readback compile all land
    outside the timed region. Completion is observed with a device_get of a
    scalar sum rather than block_until_ready (which is unreliable through
    the axon tunnel). The binding constraint of weight streaming — every
    throughput artifact should carry this number for legibility."""
    import time

    import jax

    import numpy as np

    device = device or jax.local_devices()[0]  # addressable on every rank
    buf = np.ones((mb, 1024, 1024 // 4), np.float32)
    a = jax.device_put(buf, device)  # warm: same shape/dtype as the timed put
    jax.device_get(a.sum())  # warm the readback compile too
    t0 = time.perf_counter()
    a = jax.device_put(buf, device)
    jax.device_get(a.sum())
    return buf.nbytes / 1e9 / (time.perf_counter() - t0)


def _kind_lookup(device, table) -> float | None:
    """Resolve a per-chip spec from a (device_kind substring, value) table."""
    import jax

    device = device if device is not None else jax.local_devices()[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    for token, value in table:
        if token in kind:
            return value
    return None


def chip_peak_flops(device=None) -> float | None:
    """Peak bf16 FLOP/s for one chip, or None when unknown (CPU, new kinds)."""
    return _kind_lookup(device, _PEAK_BF16_FLOPS)


# HBM per chip in GB, by device_kind substring (public TPU specs). Used by
# the resident-decode auto gate when the allocator reports no bytes_limit
# (devices behind the axon tunnel report no memory stats at all).
_HBM_GB = (
    ("v6e", 32.0),
    ("v6", 32.0),
    ("v5p", 95.0),
    ("v5e", 16.0),
    ("v5 lite", 16.0),
    ("v5litepod", 16.0),
    ("v4", 32.0),
    ("v3", 16.0),
    ("v2", 8.0),
)


def chip_hbm_gb(device=None) -> float | None:
    """HBM capacity of one chip in GB: the allocator's ``bytes_limit`` when
    it reports one, else the device-kind table, else None (unknown — e.g.
    the CPU backend, where "device memory" is host RAM)."""
    import jax

    device = device if device is not None else jax.local_devices()[0]
    try:
        stats = device.memory_stats()
        limit = (stats or {}).get("bytes_limit")
        if limit:
            return limit / 1e9
    except Exception:
        pass
    return _kind_lookup(device, _HBM_GB)


# Bytes per element at each supported compute dtype — the shared factor of
# every HBM-budget gate (resident weights, kv-on-device, fused decode).
_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2}


def weight_bytes_per_chip(cfg, dtype: str, n_chips: int = 1) -> float:
    """Materialised parameter bytes per chip at compute dtype — the shared
    numerator of the resident-decode (config.decode_resident_enabled) and
    kv-on-device / fused-decode (runtime.decode) HBM gates."""
    return param_count(cfg) * _DTYPE_BYTES[dtype] / max(n_chips, 1)


def param_count(cfg) -> int:
    """Total parameter count for a LlamaConfig — ALL weights as materialised
    on device at compute dtype (every expert, embeddings, untied head; int8
    checkpoints dequantize on placement, executor._place), the
    resident-decode sizing numerator. Shares ``_arch_walk`` with
    ``model_flops_per_token`` but counts storage instead of active
    compute."""
    h = cfg.hidden_size
    attn, moe_pattern, dense_inter = _arch_walk(cfg)
    total = 0
    for is_moe in moe_pattern:
        if is_moe:
            mlp = cfg.num_local_experts * 3 * h * cfg.intermediate_size
            mlp += h * cfg.num_local_experts  # router
            # shared expert (llama4: 1x routed width; deepseek: n_shared x)
            mlp += _shared_expert_mult(cfg) * 3 * h * cfg.intermediate_size
        else:
            mlp = 3 * h * dense_inter
        total += attn + mlp + 2 * h  # + the two norm scale vectors
    total += h * cfg.vocab_size  # embed
    if not cfg.tie_word_embeddings:
        total += h * cfg.vocab_size  # untied lm_head
    total += h  # final norm
    return int(total)


def throughput(tokens: int, seconds: float, chips: int = 1) -> dict[str, float]:
    """tokens/sec and tokens/sec/chip — the BASELINE.md headline metric."""
    tps = tokens / seconds if seconds > 0 else 0.0
    return {
        "tokens_per_sec": round(tps, 3),
        "tokens_per_sec_per_chip": round(tps / max(chips, 1), 3),
    }


__all__ = [
    "IntegrityRecorder",
    "LiveArrayPeakSampler",
    "Recorder",
    "RetryRecorder",
    "RouterMetrics",
    "ServingMetrics",
    "StepWatchdog",
    "assemble_serve_stats",
    "chip_peak_flops",
    "model_flops_per_token",
    "compiled_memory_analysis",
    "device_memory_stats",
    "peak_hbm_gb",
    "profiler_trace",
    "progress_bar",
    "throughput",
]
