"""ctypes bridge to the native C++ runtime components (native/).

The shared library is compiled on first use with g++ (cached under
``native/build/``) — no pybind11 required. Every entry point degrades to a
pure-Python equivalent when no toolchain is available, so the framework
stays importable anywhere; the native path is the production one.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from concurrent.futures import ThreadPoolExecutor

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRCS = [
    os.path.join(_ROOT, "native", "fileprefetch.cpp"),
    os.path.join(_ROOT, "native", "convert.cpp"),
]
_BUILD_DIR = os.path.join(_ROOT, "native", "build")
_SO = os.path.join(_BUILD_DIR, "fls_native.so")

_lib_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _host_build_tag() -> str:
    """Identity of the CPU the cached .so was built for. The library builds
    with -march=native, so a cached artifact that travels to a different
    machine (container image built elsewhere, shared checkout) would execute
    illegal instructions — a tag mismatch forces a rebuild instead."""
    import hashlib
    import platform

    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            flags = next((l for l in f if l.lower().startswith("flags")), "")
    except OSError:
        pass
    return hashlib.sha1((platform.machine() + flags).encode()).hexdigest()[:16]


def _load_lib() -> ctypes.CDLL | None:
    """Compile (once) and load the native library; None if unavailable."""
    global _lib, _lib_failed
    # flscheck: disable=LOCK-IO: one-time lazy compile+dlopen behind double-checked caching; every later call returns at the top of the block, and first-callers must genuinely wait for the build
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            # Missing sources must not take down an already-built library
            # (the prefetch fast path would silently degrade); rebuild only
            # when every source is present and one is newer than the .so —
            # or when the cached .so was built for a DIFFERENT CPU.
            srcs = [s for s in _SRCS if os.path.exists(s)]
            tag = _host_build_tag()
            tag_path = _SO + ".cpu"
            try:
                with open(tag_path) as f:
                    cached_tag = f.read().strip()
            except OSError:
                cached_tag = ""
            want_build = len(srcs) == len(_SRCS) and (
                not os.path.exists(_SO)
                or cached_tag != tag
                or os.path.getmtime(_SO) < max(os.path.getmtime(s) for s in srcs)
            )
            if want_build:
                os.makedirs(_BUILD_DIR, exist_ok=True)
                base = [
                    "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                    "-o", _SO, *_SRCS, "-lpthread",
                ]
                try:
                    # The library is compiled on first use ON the machine
                    # it runs on, so -march=native is safe and real:
                    # it unlocks F16C half conversion and wider vector
                    # blends for the branchless RNE (f32->bf16 measured
                    # 4.6 -> 6.4 GB/s single-thread on this host).
                    subprocess.run(
                        base[:2] + ["-march=native"] + base[2:],
                        check=True,
                        capture_output=True,
                    )
                except subprocess.CalledProcessError:
                    subprocess.run(base, check=True, capture_output=True)
                with open(tag_path, "w") as f:
                    f.write(tag)
            lib = ctypes.CDLL(_SO)
            lib.fp_create.restype = ctypes.c_void_p
            lib.fp_create.argtypes = [ctypes.c_int]
            lib.fp_prefetch.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.fp_wait_all.argtypes = [ctypes.c_void_p]
            lib.fp_destroy.argtypes = [ctypes.c_void_p]
            lib.fp_read_file.restype = ctypes.c_long
            lib.fp_read_file.argtypes = [
                ctypes.c_char_p,
                ctypes.c_void_p,
                ctypes.c_long,
            ]
            # Newer entry points bind individually: a prebuilt .so from an
            # older source set keeps its working symbols instead of taking
            # down the whole native path.
            for sym, restype, argtypes in (
                ("fp_drop_cache", ctypes.c_long, [ctypes.c_char_p]),
                (
                    "cv_convert",
                    ctypes.c_long,
                    [
                        ctypes.c_void_p,
                        ctypes.c_void_p,
                        ctypes.c_long,
                        ctypes.c_int,
                        ctypes.c_int,
                        ctypes.c_int,
                    ],
                ),
            ):
                try:
                    fn = getattr(lib, sym)
                    fn.restype = restype
                    fn.argtypes = argtypes
                except AttributeError:
                    pass  # callers probe with getattr and fall back
            _lib = lib
        except Exception:
            _lib_failed = True
        return _lib


class FilePrefetcher:
    """Warms files into the OS page cache ahead of the loader's reads.

    Native path: C++ worker pool issuing ``posix_fadvise(WILLNEED)`` — the
    kernel schedules the readahead asynchronously (DMA), so warming costs
    ~zero CPU and never contends with the caller's cast/stack work (a
    full-pread warm measured 0.66-0.88x on a 1-core host; fadvise-only
    measures 1.05x — scripts/readahead_experiment.py). Fallback: the same
    fadvise from Python. ``native`` reports which path is active.
    """

    def __init__(self, threads: int = 2):
        import threading

        lib = _load_lib()
        self._lib = lib
        self._handle = lib.fp_create(threads) if lib is not None else None
        self._pool = (
            None if lib is not None else ThreadPoolExecutor(max_workers=threads)
        )
        self._futures: list = []
        # Serializes handle/pool use against close(): an abandoned
        # producer thread may still call prefetch() while close() runs —
        # without the lock the native arm could fp_prefetch a handle
        # fp_destroy just freed (use-after-free in the C++ pool).
        self._close_lock = threading.Lock()

    @property
    def native(self) -> bool:
        return self._handle is not None

    def prefetch(self, *paths: str) -> None:
        # No-op after close(): an abandoned producer thread (a source's
        # bounded close gave up joining it) may still issue warms; readahead
        # is advisory, so dropping them is correct — crashing is not. The
        # lock fences BOTH arms against a concurrent close (native: the
        # handle must not be destroyed mid-call; python: the pool must not
        # shut down mid-submit).
        with self._close_lock:
            for p in paths:
                if self._handle is not None:
                    self._lib.fp_prefetch(self._handle, p.encode())
                elif self._pool is not None:
                    try:
                        self._futures.append(
                            self._pool.submit(self._py_warm, p)
                        )
                    except RuntimeError:  # pool shut down concurrently
                        return

    @staticmethod
    def _py_warm(path: str) -> None:
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                # Same async-kernel-readahead contract as the native path;
                # never a userspace read loop (it would steal the caster's
                # CPU — the measured failure mode of the old design).
                os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_WILLNEED)
            finally:
                os.close(fd)
        except (OSError, AttributeError):
            pass  # loader will raise the real error on its own read

    def wait_all(self) -> None:
        with self._close_lock:
            if self._handle is not None:
                # The native arm waits UNDER the fence on purpose:
                # fp_wait_all racing a concurrent close()'s fp_destroy is a
                # use-after-free, and the stall is bounded (queued kernel
                # readaheads complete on their own). Only the Python-pool
                # arm below can await off the lock — its futures outlive a
                # concurrent shutdown safely.
                self._lib.fp_wait_all(self._handle)
                return
            pending, self._futures = self._futures, []
        # Awaited OFF the fence lock: a slow warm (cold disk, deep queue)
        # must not block a concurrent prefetch()/close() on the lock —
        # the snapshot-swap above keeps the handoff race-free.
        for f in pending:
            f.result()

    def close(self) -> None:
        with self._close_lock:
            if self._handle is not None:
                self._lib.fp_destroy(self._handle)
                self._handle = None
            elif self._pool is not None:
                self._pool.shutdown(wait=False)
                self._pool = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


def available_cpus() -> int:
    """Cores this PROCESS can actually run on — affinity/cgroup aware
    (os.cpu_count reports the machine, which overcounts in containers
    pinned to a subset; convert_array's thread-count choice needs the
    real number)."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):  # non-Linux
        return os.cpu_count() or 1


# dtype kind codes shared with native/convert.cpp.
_CV_KINDS = {"float32": 0, "float16": 1, "bfloat16": 2}

# Below this element count numpy's single-threaded astype wins (thread
# spawn + two ctypes calls cost more than the conversion itself).
_CV_MIN_SIZE = 1 << 18


def convert_array(a, np_dtype, threads: int | None = None):
    """Parallel float dtype conversion (native C++ workers, numpy-bit-exact
    round-to-nearest-even) — the host-side cast of the weight-streaming
    path. Returns the converted array, or None when the native library is
    unavailable, the pair isn't a float16/bfloat16/float32 conversion, or
    the array is too small to beat ``astype``. Callers fall back to numpy.

    Single-threaded native is ALSO faster than numpy's astype — 1.5-3x
    measured per pair on a 1-core host (ml_dtypes converts element-wise;
    the native loops are branchless and vectorized, with hardware F16C
    half conversion under -march=native) — so there is no minimum core
    count: ``threads`` only bounds the parallel slicing.
    """
    import numpy as np

    np_dtype = np.dtype(np_dtype)
    sk = _CV_KINDS.get(a.dtype.name)
    dk = _CV_KINDS.get(np_dtype.name)
    if (
        sk is None
        or dk is None
        or sk == dk
        or a.size < _CV_MIN_SIZE
    ):
        return None
    if threads is None:
        threads = min(8, available_cpus())
    lib = _load_lib()
    if lib is None or getattr(lib, "cv_convert", None) is None:
        return None
    src = np.ascontiguousarray(a)
    dst = np.empty(src.shape, np_dtype)
    rc = lib.cv_convert(
        src.ctypes.data, dst.ctypes.data, src.size, sk, dk, threads
    )
    return dst if rc == 0 else None


def drop_file_cache(*paths: str) -> bool:
    """Best-effort eviction of files from the OS page cache (native
    FADV_DONTNEED). Returns True if the native lib handled every path —
    the cold-cache loader benchmark is only meaningful when it did."""
    lib = _load_lib()
    if lib is None or getattr(lib, "fp_drop_cache", None) is None:
        return False
    ok = True
    for p in paths:
        ok = lib.fp_drop_cache(p.encode()) == 0 and ok
    return ok


def read_file_native(path: str) -> bytes | None:
    """Whole-file read through the native pread loop (None if no native lib
    or on IO error) — exercised by tests; a pinned-buffer IO building block."""
    lib = _load_lib()
    if lib is None:
        return None
    size = os.path.getsize(path)
    buf = ctypes.create_string_buffer(size)
    n = lib.fp_read_file(path.encode(), buf, size)
    if n < 0:
        return None
    return buf.raw[:n]


__all__ = [
    "FilePrefetcher",
    "available_cpus",
    "convert_array",
    "drop_file_cache",
    "read_file_native",
]
