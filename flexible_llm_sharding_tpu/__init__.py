"""flexible_llm_sharding_tpu — a TPU-native layer-streaming LLM framework.

A brand-new framework with the capabilities of the reference
``flexible-LLM-sharding`` (see SURVEY.md): run unquantized large LLMs on
accelerators whose HBM is far smaller than the model by streaming per-layer
weights host->HBM shard-by-shard, scoring batches of (prefix, suffixes)
prompts with a shared prefix-KV trick, with data-parallel and interleaved
pipeline model-parallel multi-chip modes.

Built TPU-first on JAX/XLA: pure-function per-layer forwards jit-compiled
once per shape family, weights as pytrees streamed with async ``device_put``
double-buffered against compute, shardings expressed over a
``jax.sharding.Mesh`` so collectives ride ICI.
"""

__version__ = "0.1.0"

from flexible_llm_sharding_tpu.config import FrameworkConfig, LlamaConfig  # noqa: F401
