"""CLI batch driver — the reference's ``main.py`` surface, TPU-native inside.

Same flag set and pickle contracts (``/root/reference/main.py:30-49,55-58,
92-98``): input is a pickle of ``[(prefix_str, (suffix_str, ...)), ...]``;
outputs are a score pickle (one float32 ``[n_suffixes, num_gen_token, vocab]``
array per prompt) and a ``*_updated.pkl`` prompts file with generated text
appended to each suffix.

Differences, all deliberate:
- ``--data_parallel`` parses real booleans (the reference's ``type=bool``
  treats any non-empty string as True, ``/root/reference/main.py:40``).
- ``--storage_location`` accepts ``tpu`` (activations stay in HBM); ``gpu``
  is kept as an alias.
- TPU-specific knobs (``--dtype``, ``--block_size``, ``--prefetch_depth``,
  ``--num_devices``, ``--max_token_len``) extend the surface.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys

from flexible_llm_sharding_tpu.config import (
    DEFAULT_MAX_TOKEN_LEN,
    FAULT_SITES,
    FaultConfig,
    FrameworkConfig,
    PressureConfig,
)


# --- KNOB-SYNC declarations (machine-checked by flscheck, analysis/rules.py).
# A FrameworkConfig/FaultConfig flag normally belongs in BOTH parsers (the
# recurring review defect was adding a knob to one and forgetting the other);
# a flag listed here is deliberately single-parser, for the stated reason.
BATCH_ONLY_FLAGS = frozenset({
    # Workload shape of one offline batch run — serving has no fixed batch.
    "num_batch", "num_gen_token", "disk_folder", "max_activation_in_cpu",
    "resume", "long_context",
    # Multi-chip layouts: serving v1 drives a single placement target
    # (ServeEngine rejects data_parallel/tensor_parallel loudly).
    "data_parallel", "num_devices", "tensor_parallel",
    # Sampling: serving is greedy-only for now (per-request rng streams
    # under sampling are future work; ServeEngine rejects temperature > 0).
    "temperature", "top_k", "top_p", "seed",
    # KV-decode specials that don't compose with the sweep engine. NOTE:
    # the serve parser ALSO defines --speculative_k, but that one sets
    # ServeConfig.speculative_k (the serving-path speculation knob,
    # docs/speculative.md) — this declaration covers the batch parser's
    # FrameworkConfig.speculative_k (the offline scorer's knob); the two
    # are distinct fields behind one flag name, and KNOB-SYNC resolves
    # each parser's flag against its own config class.
    "decode_fused", "speculative_k",
    # Offline observability/profiling of a single run.
    "verbose_metrics", "profile_dir",
})
SERVE_ONLY_FLAGS = frozenset()
# Flags that drive the run (inputs/outputs/cluster wiring/demo pacing) and
# set no config field.
DRIVER_FLAGS = frozenset({
    "prompt_pickle", "output_file", "kv_cache",
    "coordinator_address", "num_processes", "process_id",
    "stagger_ms",
    # One-shot metrics-registry JSON dump path (batch CLI output file).
    "metrics_out",
})


def _str2bool(v: str) -> bool:
    if v.lower() in ("true", "1", "yes"):
        return True
    if v.lower() in ("false", "0", "no", ""):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {v!r}")


def _str2bool_or_auto(v: str) -> bool | None:
    if v.lower() == "auto":
        return None
    return _str2bool(v)


def _float_or_auto(v: str) -> float | None:
    if v.lower() == "auto":
        return None
    return float(v)


def _add_robustness_flags(p: argparse.ArgumentParser) -> None:
    """Shared by the batch and serve parsers: transient-I/O retry knobs and
    the deterministic chaos (fault-injection) switch."""
    p.add_argument("--io_retry_attempts", type=int, default=4,
                   help="attempts per weight-stream I/O call (layer read, "
                        "host->device put) before a typed ShardLoadError "
                        "surfaces; 1 disables retrying")
    p.add_argument("--io_retry_base_s", type=float, default=0.05,
                   help="first retry backoff; doubles per attempt (jittered)")
    p.add_argument("--io_retry_deadline_s", type=float, default=60.0,
                   help="overall wall cap per retried call (0 = none)")
    p.add_argument("--chaos", action="store_true",
                   help="enable deterministic fault injection at the named "
                        "sites (faults/inject.py) — proves the retry/degrade "
                        "layer on real workloads without waiting for real "
                        "outages; off = zero overhead")
    p.add_argument("--chaos_seed", type=int, default=0,
                   help="injection schedule seed (same seed = same faults)")
    p.add_argument("--chaos_error_rate", type=float, default=0.1,
                   help="probability of an injected IOError per site fire")
    p.add_argument("--chaos_truncate_rate", type=float, default=0.0,
                   help="probability of an injected truncated read")
    p.add_argument("--chaos_latency_rate", type=float, default=0.0,
                   help="probability of an injected latency spike")
    p.add_argument("--chaos_sites", type=str, default="",
                   help=f"comma list of sites to inject at (default all): "
                        f"{','.join(FAULT_SITES)}")
    p.add_argument("--chaos_max_faults", type=int, default=-1,
                   help="total faults injected before the schedule goes "
                        "permanently clean (-1 = unlimited) — models a "
                        "transient outage that ENDS; e.g. replica_kill "
                        "with a budget of 1 kills exactly one replica and "
                        "lets the fleet prove clean failover")
    p.add_argument("--verify_weights", type=_str2bool, default=True,
                   help="checksum-verify every streamed layer against the "
                        "model dir's integrity.json (mismatches re-read to "
                        "heal page-cache corruption; persistent corruption "
                        "raises a typed ShardCorruptError). The crc pass is "
                        "amortized: each file generation is hashed once and "
                        "later sweeps reuse the cached verdict. false skips "
                        "it entirely on a trusted medium")
    p.add_argument("--host_cache_gb", type=_float_or_auto, default=None,
                   help="host-resident shard cache budget in GB: warm "
                        "sweeps (serving, multi-token decode, multi-batch "
                        "runs) skip disk read+parse+checksum and go "
                        "straight to device_put. 'auto' (default) = a "
                        "fraction of free RAM (off under --chaos); 0 = off")
    p.add_argument("--hbm_pin_gb", type=_float_or_auto, default=0.0,
                   help="device residency tier budget in GB: pin the "
                        "hottest layers (embedding, lm_head, norms, then "
                        "as many transformer blocks as fit) permanently in "
                        "HBM and stream only the rest — every sweep's "
                        "host->HBM traffic drops by exactly the pinned "
                        "bytes, outputs token-identical. 'auto' = measured "
                        "free HBM minus activation headroom (off under "
                        "--chaos and on unknown chips); 0 (default) = off")
    p.add_argument("--kv_page_tokens", type=int, default=16,
                   help="rows per paged prefix-KV page (runtime/kvpool.py) "
                        "— the cross-wave sharing granularity; <= 0 "
                        "disables the pool")
    p.add_argument("--kv_pool_gb", type=_float_or_auto, default=None,
                   help="host-RAM budget in GB for resident prefix-KV "
                        "pages: a recurring prefix prefills once per "
                        "PROCESS and later same-prefix waves reuse its "
                        "pages (refcounted, copy-on-write). 'auto' "
                        "(default) = a small slice of free RAM (stays on "
                        "under --chaos: spill reads are chaos sites); "
                        "0 = off")
    p.add_argument("--kv_host_spill", type=_str2bool, default=True,
                   help="true (default): cold prefix-KV pages spill to "
                        "checksummed disk files that heal on read "
                        "(re-read + .crc sidecars, typed SpillCorruptError "
                        "when corruption persists); false: drop them and "
                        "re-prefill on next use")
    p.add_argument("--readahead_threads", type=int, default=2,
                   help="threads in the loader's page-cache readahead pool "
                        "(posix_fadvise issuers, ~zero CPU each)")
    p.add_argument("--score_sink_max_device", type=int, default=16,
                   help="max head-stage score slices kept device-resident "
                        "before older ones resolve to host numpy (bigger = "
                        "fewer host syncs on big batches, more HBM)")


def _add_adapter_flags(p: argparse.ArgumentParser) -> None:
    """Shared by the batch and serve parsers: multi-tenant LoRA adapter
    serving (adapters/; docs/adapters.md has the registry layout, the
    apply math, and the one-base-stream accounting)."""
    p.add_argument("--adapter_dir", type=str, default="",
                   help="registry root of named LoRA adapters — one "
                        "subdir per adapter holding per-layer delta "
                        "safetensors + adapter_plan.json + an integrity "
                        "manifest (build one from a HF PEFT checkpoint "
                        "with `prepare-adapter`). Requests carrying an "
                        "adapter_id decode under that adapter's low-rank "
                        "delta INSIDE the shared base-model sweep: N "
                        "tenants ride one base stream for near-zero "
                        "extra link bytes. Empty (default) = adapter "
                        "serving off — adapter_id requests are rejected "
                        "typed and the sweep is byte-identical to a "
                        "build without adapters")
    p.add_argument("--adapter_max_gb", type=_float_or_auto, default=None,
                   help="host-resident adapter-factor LRU budget in GB "
                        "(adapters/loader.py, the delta-weight analog of "
                        "--host_cache_gb): 'auto' (default) = a small "
                        "fraction of free RAM — auto stays ON under "
                        "--chaos, unlike the shard cache, because the "
                        "delta reads are themselves chaos sites; "
                        "0 = adapter serving off even with --adapter_dir")


def _adapter_config_from_args(args: argparse.Namespace):
    from flexible_llm_sharding_tpu.config import AdapterConfig

    return AdapterConfig(
        dir=args.adapter_dir,
        max_gb=args.adapter_max_gb,
    )


def _add_pressure_flags(p: argparse.ArgumentParser) -> None:
    """Shared by the batch and serve parsers: the resource-pressure
    brownout controller (runtime/pressure.py; docs/pressure.md has the
    ladder stages and recovery semantics)."""
    p.add_argument("--pressure", action="store_true",
                   help="enable the brownout controller: monitor host "
                        "RAM, spill-disk space, HBM headroom, and the "
                        "host->HBM link; under sustained pressure walk a "
                        "reversible degradation ladder (shrink the host "
                        "cache, evict pooled prefix-KV pages, evict "
                        "residency pins, shed admissions with typed "
                        "Overloaded rejections, drain fleet "
                        "replicas) instead of dying — and step back down "
                        "when pressure lifts. Off = zero overhead")
    p.add_argument("--pressure_poll_s", type=float, default=1.0,
                   help="pressure-monitor sampling interval (seconds)")
    p.add_argument("--pressure_host_min_gb", type=float, default=1.0,
                   help="MemAvailable floor in GB; below it the ladder "
                        "steps up (0 = host signal off)")
    p.add_argument("--pressure_disk_min_gb", type=float, default=1.0,
                   help="spill-disk (--disk_folder filesystem) free-bytes "
                        "floor in GB (0 = disk signal off)")
    p.add_argument("--pressure_hbm_headroom_frac", type=float, default=0.05,
                   help="device free/limit HBM headroom floor (0 = off)")
    p.add_argument("--pressure_link_min_gbps", type=float, default=0.0,
                   help="host->HBM streamed-bytes rate floor in GB/s "
                        "while streaming (0 = link signal off)")
    p.add_argument("--pressure_cache_shrink_frac", type=float, default=0.5,
                   help="ladder level 1: host shard cache budget "
                        "multiplier (LRU-evicts down to this fraction)")
    p.add_argument("--pressure_shed_retry_after_s", type=float, default=1.0,
                   help="retry-after hint carried by Overloaded "
                        "rejections while shedding (ladder level 3)")
    p.add_argument("--pressure_step_down_polls", type=int, default=3,
                   help="consecutive clean polls required per ladder "
                        "step DOWN (hysteresis against flapping)")


def _add_observability_flags(p: argparse.ArgumentParser) -> None:
    """Shared by the batch and serve parsers: sweep-timeline tracing
    (obs/trace.py; docs/observability.md has the span model and the
    Perfetto how-to)."""
    p.add_argument("--trace", action="store_true",
                   help="record the sweep timeline (shard loads, device "
                        "puts, compute, source waits, cache hits, pin "
                        "loads, retry/heal events, serve wave lifecycle) "
                        "into a bounded ring, exported at run end to "
                        "--trace_out; analyze with `trace-report` or load "
                        "in Perfetto. Off = zero overhead")
    p.add_argument("--trace_out", type=str, default="",
                   help="trace export path (default fls_trace.json): "
                        "Chrome trace-event JSON, or JSONL when the path "
                        "ends in .jsonl")
    # Black-box flight recorder (obs/events.py + obs/incident.py;
    # docs/incidents.md).
    p.add_argument("--journal_dir", type=str, default="",
                   help="durable append-only JSONL event journal: every "
                        "failure-path event (engine recoveries, wave "
                        "aborts, replica death/drain/redispatch, "
                        "quarantines, heals, pressure steps, watchdog "
                        "stalls, preemptions, SLO budget exhaustion) is "
                        "written here with monotonic seq + correlation "
                        "ids, surviving the process that emitted it. "
                        "Rotates atomically at --journal_max_mb; a write "
                        "failure degrades to a counted drop "
                        "(fls_journal_events_dropped), never an error. "
                        "Empty = off (zero overhead)")
    p.add_argument("--journal_max_mb", type=float, default=16.0,
                   help="journal rotation size in MB (one previous "
                        "generation is kept)")
    p.add_argument("--incidents_dir", type=str, default="",
                   help="arm the incident recorder: a journal event at "
                        "(or above) --incident_trigger severity captures "
                        "a self-contained bundle dir here — journal "
                        "tail, full metrics snapshot, trace ring as "
                        "Chrome trace JSON, resolved config, manifest — "
                        "debounced so a failure storm yields ONE bundle. "
                        "Disk-budgeted (--incidents_max_mb), oldest "
                        "bundle evicted first. Inspect with `cli "
                        "incidents list/show/analyze`. Empty = off")
    p.add_argument("--incidents_max_mb", type=float, default=256.0,
                   help="incidents dir disk budget in MB (oldest bundles "
                        "evicted; the newest always survives)")
    p.add_argument("--incident_trigger", type=str, default="error",
                   choices=("info", "warning", "error", "critical"),
                   help="minimum journal-event severity that captures an "
                        "incident bundle")
    p.add_argument("--incident_debounce_s", type=float, default=60.0,
                   help="after a capture, trigger events within this "
                        "window only count (fls_journal_debounces) — a "
                        "failure storm yields one bundle, not hundreds")
    p.add_argument("--incident_settle_s", type=float, default=1.0,
                   help="capture settles this long after the trigger "
                        "(extended while trigger-severity events keep "
                        "landing, bounded) so the whole storm — replica "
                        "death, re-dispatch, recycle — lands inside the "
                        "bundle's journal tail; 0 = capture immediately")


def _add_sched_flags(p: argparse.ArgumentParser) -> None:
    """Serve parser only: the multi-tenant sweep scheduler
    (serve/sched/; docs/scheduling.md has the class semantics, fairness
    math, preemption state machine, and coalescing contract)."""
    p.add_argument("--sched", action="store_true",
                   help="enable the multi-tenant sweep scheduler: strict "
                        "SLO-class priority (interactive > standard > "
                        "best_effort) with deficit-weighted round-robin "
                        "across tenants inside a class, per-tenant token-"
                        "bucket rate limits (typed RateLimited with a "
                        "retry-after hint), sweep-boundary preemption of "
                        "best-effort waves by waiting interactive work "
                        "(resumed token-identically), and same-prefix "
                        "request coalescing into one shared prefill. "
                        "Off = the plain FIFO admission path")
    p.add_argument("--sched_interactive_deadline_s", type=float, default=0.0,
                   help="default admission deadline for interactive "
                        "requests that name none (0 = fall back to "
                        "--deadline_s)")
    p.add_argument("--sched_standard_deadline_s", type=float, default=0.0,
                   help="default admission deadline for standard requests "
                        "(0 = fall back to --deadline_s)")
    p.add_argument("--sched_best_effort_deadline_s", type=float, default=0.0,
                   help="default admission deadline for best_effort "
                        "requests (0 = fall back to --deadline_s)")
    p.add_argument("--sched_tenant_weights", type=str, default="",
                   help="deficit-round-robin weights, 'tenantA=4,tenantB=1' "
                        "(unlisted tenants weigh 1): a weight-w tenant "
                        "gets ~w shares of each class's admission budget "
                        "while backlogged")
    p.add_argument("--sched_tenant_limits", type=str, default="",
                   help="token-bucket rate limits in requests/second, "
                        "'tenantA=5' (unlisted = unlimited); over-limit "
                        "submits resolve as typed RateLimited carrying "
                        "retry_after_s")
    p.add_argument("--sched_tenant_burst", type=float, default=4.0,
                   help="token-bucket capacity (burst requests) for every "
                        "rate-limited tenant")
    p.add_argument("--sched_preempt", type=_str2bool, default=True,
                   help="allow a waiting interactive request to retire the "
                        "youngest best-effort wave at a shard-0 boundary "
                        "(never mid-sweep); the preempted requests resume "
                        "token-identically with their generated-so-far "
                        "tokens folded into the prefill")
    p.add_argument("--sched_coalesce", type=_str2bool, default=True,
                   help="merge same-tokenized-prefix requests admitted at "
                        "one boundary into a single wave entry that "
                        "prefills the shared prefix KV once")
    p.add_argument("--sched_interactive_phase_boost", type=float, default=2.0,
                   help="fleet routing: multiply the router's phase weight "
                        "by this for interactive requests, so they land "
                        "on the replica nearest its next shard-0 "
                        "admission point (1 = no boost)")


def _add_slo_flags(p: argparse.ArgumentParser) -> None:
    """Serve parser only: SLO targets + error budgets (obs/slo.py;
    docs/incidents.md has the budget math)."""
    p.add_argument("--slo", action="store_true",
                   help="enable SLO error-budget tracking over the "
                        "per-class latency streams: per-class p95 TTFT "
                        "targets, an aggregate per-token-latency target, "
                        "and an availability target export fls_slo_* "
                        "burn-rate/remaining-budget gauges, and a class "
                        "that exhausts its budget emits an "
                        "slo_budget_exhausted journal event (capturing "
                        "an incident bundle when the recorder is armed). "
                        "Off = the per-class exports carry no contract")
    p.add_argument("--slo_ttft_p95_s", type=str, default="",
                   help="per-class p95 TTFT targets in seconds, "
                        "'interactive=0.5,standard=2.0' (unlisted "
                        "classes carry no target)")
    p.add_argument("--slo_token_latency_p95_s", type=float, default=0.0,
                   help="aggregate per-token decode-latency p95 target "
                        "in seconds (0 = off)")
    p.add_argument("--slo_availability_target", type=float, default=0.0,
                   help="fraction of requests that must complete, e.g. "
                        "0.999 — failures burn the 1-target budget "
                        "(0 = off)")
    p.add_argument("--slo_min_samples", type=int, default=20,
                   help="budgets are not judged below this many samples "
                        "(a single slow first request must not page)")


def _slo_config_from_args(args: argparse.Namespace):
    from flexible_llm_sharding_tpu.config import SLOConfig

    if not args.slo:
        return SLOConfig()
    return SLOConfig(
        enabled=True,
        ttft_p95_s=args.slo_ttft_p95_s,
        token_latency_p95_s=args.slo_token_latency_p95_s,
        availability_target=args.slo_availability_target,
        min_samples=args.slo_min_samples,
    )


def _add_autoscale_flags(p: argparse.ArgumentParser) -> None:
    """Serve parser only: closed-loop fleet elasticity + sweep-phase
    stagger (serve/autoscale.py; docs/autoscale.md)."""
    p.add_argument("--autoscale", action="store_true",
                   help="enable the fleet autoscaler: a control loop "
                        "polls SLO burn rate, queue depth, and the "
                        "brownout pressure level and grows/drains the "
                        "replica fleet between --autoscale_min/max with "
                        "anti-flap hysteresis (consecutive-poll "
                        "confirmation, per-direction cooldowns) and hard "
                        "interlocks (never grow at shed-or-above "
                        "pressure; never shrink below min or over an "
                        "in-flight drain; WAL replay completes first). "
                        "Also engages the sweep-phase stagger controller "
                        "(replica offsets held at i/N so worst-case "
                        "admission wait is sweep/N). Off = static fleet, "
                        "free-drifting phases")
    p.add_argument("--autoscale_min", type=int, default=1,
                   help="fleet size floor the controller may drain to")
    p.add_argument("--autoscale_max", type=int, default=4,
                   help="fleet size ceiling the controller may grow to")
    p.add_argument("--autoscale_poll_s", type=float, default=1.0,
                   help="controller poll interval in seconds (decisions "
                        "at most once per poll)")
    p.add_argument("--autoscale_grow_burn_rate", type=float, default=1.0,
                   help="grow when the worst per-class SLO burn rate "
                        "sustains at or above this (1.0 = spending the "
                        "entire error budget)")
    p.add_argument("--autoscale_grow_queue_frac", type=float, default=0.75,
                   help="grow when queue depth / capacity sustains at or "
                        "above this fraction")
    p.add_argument("--autoscale_shrink_burn_rate", type=float, default=0.25,
                   help="shrink only when burn rate AND queue fraction "
                        "are both below their shrink thresholds "
                        "(hysteresis: must be <= the grow threshold)")
    p.add_argument("--autoscale_shrink_queue_frac", type=float, default=0.10,
                   help="queue-fraction half of the shrink band "
                        "(must be <= the grow fraction)")
    p.add_argument("--autoscale_confirm_polls", type=int, default=3,
                   help="a breach must persist this many CONSECUTIVE "
                        "polls before the controller acts — one spiky "
                        "sample never scales the fleet")
    p.add_argument("--autoscale_grow_cooldown_s", type=float, default=10.0,
                   help="after any scale action, grow again only after "
                        "this many seconds")
    p.add_argument("--autoscale_shrink_cooldown_s", type=float, default=30.0,
                   help="after any scale action, shrink only after this "
                        "many seconds (longer than grow by default: "
                        "capacity is cheap to hold, expensive to miss)")
    p.add_argument("--autoscale_dry_run", action="store_true",
                   help="journal every decision (autoscale_* events with "
                        "dry_run=true) without acting — shadow-mode "
                        "rehearsal before trusting the loop")
    p.add_argument("--autoscale_no_stagger", action="store_true",
                   help="disable the sweep-phase stagger controller "
                        "(replica offsets drift free again)")
    p.add_argument("--autoscale_stagger_tolerance", type=float,
                   default=0.15,
                   help="normalized stagger error at or under this "
                        "counts as converged (0 = perfect i/N spread, "
                        "1 = all replicas in phase)")
    p.add_argument("--autoscale_stagger_hold_max_frac", type=float,
                   default=0.5,
                   help="cap on a single boundary hold as a fraction of "
                        "one measured sweep wall")


def _autoscale_config_from_args(args: argparse.Namespace):
    from flexible_llm_sharding_tpu.config import AutoscaleConfig

    if not args.autoscale:
        return AutoscaleConfig()
    return AutoscaleConfig(
        enabled=True,
        min=args.autoscale_min,
        max=args.autoscale_max,
        poll_s=args.autoscale_poll_s,
        grow_burn_rate=args.autoscale_grow_burn_rate,
        grow_queue_frac=args.autoscale_grow_queue_frac,
        shrink_burn_rate=args.autoscale_shrink_burn_rate,
        shrink_queue_frac=args.autoscale_shrink_queue_frac,
        confirm_polls=args.autoscale_confirm_polls,
        grow_cooldown_s=args.autoscale_grow_cooldown_s,
        shrink_cooldown_s=args.autoscale_shrink_cooldown_s,
        dry_run=args.autoscale_dry_run,
        stagger=not args.autoscale_no_stagger,
        stagger_tolerance=args.autoscale_stagger_tolerance,
        stagger_hold_max_frac=args.autoscale_stagger_hold_max_frac,
    )


def _serve_wants_fleet(serve_cfg) -> bool:
    """True when serve must run the replica fleet instead of a single
    engine: more than one replica, or elasticity requested. The
    autoscaler lives in ReplicaFleet, and "start at one replica, grow
    under load" is the canonical elastic config — gating on the replica
    count alone would silently disable ``--autoscale`` exactly there."""
    return serve_cfg.replicas > 1 or serve_cfg.autoscale.enabled


def _sched_config_from_args(args: argparse.Namespace):
    from flexible_llm_sharding_tpu.config import SchedConfig

    if not args.sched:
        return SchedConfig()
    return SchedConfig(
        enabled=True,
        interactive_deadline_s=args.sched_interactive_deadline_s,
        standard_deadline_s=args.sched_standard_deadline_s,
        best_effort_deadline_s=args.sched_best_effort_deadline_s,
        tenant_weights=args.sched_tenant_weights,
        tenant_limits=args.sched_tenant_limits,
        tenant_burst=args.sched_tenant_burst,
        preempt=args.sched_preempt,
        coalesce=args.sched_coalesce,
        interactive_phase_boost=args.sched_interactive_phase_boost,
    )


def _pressure_config_from_args(args: argparse.Namespace) -> PressureConfig:
    if not args.pressure:
        return PressureConfig()
    return PressureConfig(
        enabled=True,
        poll_s=args.pressure_poll_s,
        host_min_gb=args.pressure_host_min_gb,
        disk_min_gb=args.pressure_disk_min_gb,
        hbm_headroom_frac=args.pressure_hbm_headroom_frac,
        link_min_gbps=args.pressure_link_min_gbps,
        cache_shrink_frac=args.pressure_cache_shrink_frac,
        shed_retry_after_s=args.pressure_shed_retry_after_s,
        step_down_polls=args.pressure_step_down_polls,
    )


def _fault_config_from_args(args: argparse.Namespace) -> FaultConfig:
    if not args.chaos:
        return FaultConfig()
    return FaultConfig(
        enabled=True,
        seed=args.chaos_seed,
        error_rate=args.chaos_error_rate,
        truncate_rate=args.chaos_truncate_rate,
        latency_rate=args.chaos_latency_rate,
        sites=tuple(s for s in args.chaos_sites.split(",") if s),
        max_faults=args.chaos_max_faults,
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="flexible-llm-sharding-tpu",
        description="Layer-streaming LLM scorer/generator for TPU",
    )
    p.add_argument("--model_path", type=str, default="./")
    p.add_argument("--prompt_pickle", type=str, required=True,
                   help="Path to the input prompt pickle file")
    p.add_argument("--output_file", type=str, required=True,
                   help="Path to the LLM output scores file")
    p.add_argument("--num_batch", type=int, default=1)
    p.add_argument("--layer_num_per_shard", type=int, default=1)
    p.add_argument("--storage_location", type=str, default="cpu",
                   help="'tpu' (HBM), 'cpu' (host RAM), or 'disk'; 'gpu' = alias of 'tpu'")
    p.add_argument("--max_activation_in_cpu", type=int, default=100)
    p.add_argument("--data_parallel", type=_str2bool, default=False,
                   help="True: split prompts across chips; False: interleaved layer pipeline across chips")
    p.add_argument("--disk_folder", type=str, default="./temp")
    p.add_argument("--num_gen_token", type=int, default=1,
                   help="how many new tokens to be generated")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy (reference behaviour); >0 samples p^(1/T)")
    p.add_argument("--top_k", type=int, default=0,
                   help="sampling: keep only the k most probable tokens (0 = off)")
    p.add_argument("--top_p", type=float, default=0.0,
                   help="sampling: nucleus truncation at cumulative mass p (0 = off)")
    p.add_argument("--seed", type=int, default=0,
                   help="sampling rng seed (temperature > 0)")
    p.add_argument("--kv_cache", type=_str2bool, default=False,
                   help="fast generation: reuse per-layer KV across tokens "
                        "(token-id append semantics; greedy or sampled)")
    p.add_argument("--decode_resident", type=str, default="auto",
                   choices=("auto", "on", "off"),
                   help="kv_cache mode: keep streamed weights on chip after "
                        "prefill when they fit (auto = judge against the "
                        "chip's HBM), so decode steps move zero weight bytes")
    p.add_argument("--decode_fused", type=str, default="auto",
                   choices=("auto", "on", "off"),
                   help="resident kv_cache mode: run ALL greedy decode steps "
                        "as one compiled program per block (on-device argmax, "
                        "zero per-token host round-trips); 'on' errors if the "
                        "preconditions don't hold")
    p.add_argument("--speculative_k", type=int, default=0,
                   help="kv_cache mode: verify this many prompt-lookup "
                        "drafted tokens per streamed pass (greedy-exact; "
                        "divides weight streams per token by the acceptance "
                        "factor when the model must re-stream); 0 = off")
    # --- TPU-specific ---
    p.add_argument("--dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float16", "float32"])
    p.add_argument("--block_size", type=int, default=8)
    p.add_argument("--prefetch_depth", type=int, default=None,
                   help="shards uploaded ahead of compute; default auto "
                        "(2 on TPU, 0 on the CPU backend where there is no "
                        "host->device link to overlap); 0 = serialized")
    p.add_argument("--num_devices", type=int, default=0, help="0 = all visible chips")
    p.add_argument("--bucket_multiple", type=int, default=64,
                   help="sequence lengths padded up to a multiple of this "
                        "(fewer jit shapes; more padding)")
    p.add_argument("--tensor_parallel", type=int, default=1,
                   help="shard every streamed layer's matmuls over this many "
                        "chips (Megatron layout over ICI); cuts per-chip "
                        "weight HBM by the factor. 1 = off")
    p.add_argument("--max_token_len", type=int, default=DEFAULT_MAX_TOKEN_LEN)
    p.add_argument("--use_pallas", type=_str2bool_or_auto, default=None,
                   help="Pallas flash-attention kernels: true/false, or "
                        "'auto' (default: on when running on real TPU, "
                        "where they bench 2-3.5x faster at 4k context)")
    p.add_argument("--verbose_metrics", type=_str2bool, default=False,
                   help="emit one JSON line per structured timing event")
    p.add_argument("--profile_dir", type=str, default="",
                   help="write a jax.profiler (Perfetto/XProf) trace here")
    p.add_argument("--resume", type=_str2bool, default=False,
                   help="disk mode: resume a crashed run from the last "
                        "completed shard (single-device/DP) or pipeline "
                        "stage (MP)")
    p.add_argument("--long_context", type=_str2bool, default=False,
                   help="score prefixes longer than max_token_len exactly "
                        "via sequence parallelism (cap becomes "
                        "n_chips * max_token_len) instead of truncating")
    p.add_argument("--coordinator_address", type=str, default=None,
                   help="multi-host (DCN) cluster coordinator, host:port; "
                        "omit for single-host")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--metrics_out", type=str, default="",
                   help="write a one-shot JSON dump of the metrics "
                        "registry (executor/stream/cache/residency/"
                        "integrity counters — the machine-readable form "
                        "of the final stats line) to this path at run end")
    _add_robustness_flags(p)
    _add_adapter_flags(p)
    _add_pressure_flags(p)
    _add_observability_flags(p)
    return p


def config_from_args(args: argparse.Namespace) -> FrameworkConfig:
    return FrameworkConfig(
        model_path=args.model_path,
        num_batch=args.num_batch,
        layer_num_per_shard=args.layer_num_per_shard,
        storage_location=args.storage_location,
        max_activation_in_cpu=args.max_activation_in_cpu,
        data_parallel=args.data_parallel,
        disk_folder=args.disk_folder,
        num_gen_token=args.num_gen_token,
        max_token_len=args.max_token_len,
        dtype=args.dtype,
        block_size=args.block_size,
        prefetch_depth=args.prefetch_depth,
        num_devices=args.num_devices,
        bucket_multiple=args.bucket_multiple,
        tensor_parallel=args.tensor_parallel,
        use_pallas=args.use_pallas,
        verbose_metrics=args.verbose_metrics,
        profile_dir=args.profile_dir,
        resume=args.resume,
        long_context=args.long_context,
        decode_resident=args.decode_resident,
        decode_fused=args.decode_fused,
        speculative_k=args.speculative_k,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        seed=args.seed,
        io_retry_attempts=args.io_retry_attempts,
        io_retry_base_s=args.io_retry_base_s,
        io_retry_deadline_s=args.io_retry_deadline_s,
        verify_weights=args.verify_weights,
        host_cache_gb=args.host_cache_gb,
        kv_page_tokens=args.kv_page_tokens,
        kv_pool_gb=args.kv_pool_gb,
        kv_host_spill=args.kv_host_spill,
        hbm_pin_gb=args.hbm_pin_gb,
        readahead_threads=args.readahead_threads,
        score_sink_max_device=args.score_sink_max_device,
        trace=args.trace,
        trace_out=args.trace_out,
        journal_dir=args.journal_dir,
        journal_max_mb=args.journal_max_mb,
        incidents_dir=args.incidents_dir,
        incidents_max_mb=args.incidents_max_mb,
        incident_trigger=args.incident_trigger,
        incident_debounce_s=args.incident_debounce_s,
        incident_settle_s=args.incident_settle_s,
        faults=_fault_config_from_args(args),
        pressure=_pressure_config_from_args(args),
        adapters=_adapter_config_from_args(args),
    )


def _updated_path(p: str, rank: int | None = None) -> str:
    # Robust form of the reference's .replace('.pkl', '_updated.pkl')
    # contract (/root/reference/main.py:92-94): only the extension is
    # rewritten, so an input without '.pkl' is never silently clobbered.
    root, ext = os.path.splitext(p)
    tag = "_updated" if rank is None else f"_updated.rank{rank}"
    return f"{root}{tag}{ext or '.pkl'}"


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="flexible-llm-sharding-tpu serve",
        description="Online serving: shard-aware continuous batching over "
        "the streaming runtime. Requests join at shard-0 boundaries of the "
        "decode sweep; in-flight requests are never re-prefilled.",
    )
    p.add_argument("--model_path", type=str, default="./")
    # Runtime knobs shared with the offline CLI.
    p.add_argument("--dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float16", "float32"])
    p.add_argument("--layer_num_per_shard", type=int, default=1)
    p.add_argument("--storage_location", type=str, default="cpu",
                   help="'tpu' parks per-wave KV in HBM; 'cpu' in host RAM")
    p.add_argument("--block_size", type=int, default=8)
    p.add_argument("--bucket_multiple", type=int, default=64)
    p.add_argument("--prefetch_depth", type=int, default=None)
    p.add_argument("--max_token_len", type=int, default=DEFAULT_MAX_TOKEN_LEN)
    p.add_argument("--use_pallas", type=_str2bool_or_auto, default=None)
    p.add_argument("--decode_resident", type=str, default="auto",
                   choices=("auto", "on", "off"),
                   help="keep the model on chip across sweeps when it fits "
                        "(auto judges against the chip's HBM); off "
                        "re-streams the weights every sweep (the large-"
                        "model regime)")
    # Serving knobs (ServeConfig).
    p.add_argument("--queue_capacity", type=int, default=64,
                   help="admission queue bound; submissions beyond it are "
                        "rejected with a reason (backpressure)")
    p.add_argument("--max_wave_requests", type=int, default=8,
                   help="requests coalesced into one wave at a shard-0 "
                        "boundary (the prefill batch size)")
    p.add_argument("--max_active_requests", type=int, default=32,
                   help="total in-flight requests across all waves")
    p.add_argument("--max_new_tokens", type=int, default=16,
                   help="per-request generation budget (requests may "
                        "carry their own in jsonl mode)")
    p.add_argument("--deadline_s", type=float, default=0.0,
                   help="queue-wait deadline: a request not admitted "
                        "within this many seconds is evicted as expired "
                        "(0 = none)")
    p.add_argument("--stats_interval_s", type=float, default=10.0,
                   help="periodic structured serve-stats JSON line on "
                        "stderr (0 = off)")
    p.add_argument("--watchdog_abort_s", type=float, default=600.0,
                   help="streamed-weights mode: abort and recover a sweep "
                        "that makes no shard progress for this long — the "
                        "stalled wave's requests fail with a structured "
                        "error instead of hanging forever (0 = off)")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve a Prometheus /metrics endpoint (plus "
                        "/metrics.json) on 127.0.0.1 at this port: queue "
                        "depth, TTFT quantiles, streamed bytes, cache hit "
                        "rate, residency savings, retry/heal/recovery "
                        "counters in one scrape; 0 = ephemeral port, "
                        "omit = off")
    # Replica fleet (serve/fleet.py): N engines behind a shard-phase-aware
    # router with health-driven draining and exactly-once re-dispatch.
    p.add_argument("--replicas", type=int, default=1,
                   help="serving engine replicas (thread-per-engine, one "
                        "process, shared host shard cache). >1 runs the "
                        "replica fleet: requests route to the healthiest "
                        "replica by shard-phase proximity + queue depth, "
                        "and a dead replica's queued/in-flight requests "
                        "re-dispatch to a survivor exactly once, "
                        "token-identically")
    p.add_argument("--router_phase_weight", type=float, default=1.0,
                   help="router score weight on sweep-phase proximity "
                        "(fraction of a sweep until the replica's next "
                        "shard-0 admission point)")
    p.add_argument("--router_depth_weight", type=float, default=1.0,
                   help="router score weight on normalized queue depth "
                        "((queued + active) / max_active_requests)")
    p.add_argument("--router_health_poll_s", type=float, default=0.2,
                   help="fleet health-monitor poll interval: each tick "
                        "reads per-replica registry health + the sweep "
                        "liveness watermark (a busy replica stalled past "
                        "--watchdog_abort_s is hard-failed)")
    p.add_argument("--router_drain_recoveries", type=int, default=0,
                   help="gracefully drain + recycle a replica whose "
                        "engine_recoveries counter reaches this (a flaky-"
                        "but-alive engine); 0 = off")
    p.add_argument("--max_request_tokens", type=int, default=0,
                   help="admission-side request size cap: estimated "
                        "prompt tokens (longest suffix included) + "
                        "max_new_tokens above this are rejected typed "
                        "(RequestTooLarge) at submit, before they can "
                        "join a wave and fail it at allocation; 0 = off")
    p.add_argument("--speculative_k", type=int, default=0,
                   help="speculative decoding on the serving path: each "
                        "in-flight request drafts this many prompt-lookup "
                        "tokens per sweep and the engine verifies all "
                        "drafts batch-wide inside the SAME weight sweep "
                        "(K+1-slot verify pass) — accepted drafts "
                        "multiply tokens-per-sweep at no extra stream "
                        "cost, and output stays token-identical to 0 "
                        "(greedy-exact verification); 0 = off")
    p.add_argument("--draft_model_path", type=str, default="",
                   help="resident draft model (docs/speculative.md): "
                        "checkpoint dir of a SMALL model pinned whole on "
                        "chip through its own residency tier and used as "
                        "the speculative draft source instead of prompt "
                        "lookup — draft decode runs against the pinned "
                        "weights, adding ZERO bytes to the per-sweep "
                        "weight stream; '' = off (prompt-lookup drafts)")
    p.add_argument("--spec_adaptive", action="store_true",
                   help="SLO-aware adaptive draft depth (serve/spec.py): "
                        "per-class k follows windowed live acceptance "
                        "between --spec_k_min and --spec_k_max, funds "
                        "interactive rows first under --spec_draft_budget, "
                        "and backs off to 0 as the brownout ladder's first "
                        "lever; requires --speculative_k >= 1 (starting k)")
    p.add_argument("--spec_k_min", type=int, default=0,
                   help="adaptive-k lower bound (0 lets a class stop "
                        "drafting entirely when drafts keep missing)")
    p.add_argument("--spec_k_max", type=int, default=8,
                   help="adaptive-k upper bound; the verify slot budget is "
                        "provisioned at this k so k can grow mid-wave")
    p.add_argument("--spec_window", type=int, default=8,
                   help="acceptance window: a class's k moves only after "
                        "this many observed drafting passes")
    p.add_argument("--spec_raise_threshold", type=float, default=0.6,
                   help="raise a class's k when its windowed acceptance "
                        "reaches this")
    p.add_argument("--spec_backoff_threshold", type=float, default=0.2,
                   help="shrink a class's k when its windowed acceptance "
                        "falls to this or below")
    p.add_argument("--spec_draft_budget", type=int, default=0,
                   help="per-pass draft-token budget across the wave, "
                        "spent in strict SLO-class priority order "
                        "(interactive first); 0 = unlimited")
    p.add_argument("--wal_dir", type=str, default="",
                   help="crash-safe serving (docs/recovery.md): directory "
                        "for the durable request WAL — every admission, "
                        "sweep-boundary progress mark, and terminal "
                        "outcome is journaled, and on the next start "
                        "every still-open request is replayed "
                        "token-identically before new traffic is "
                        "accepted; empty = WAL off")
    p.add_argument("--wal_fsync", type=str, default="admit",
                   choices=["always", "admit", "never"],
                   help="WAL durability/throughput trade: 'always' fsyncs "
                        "every record, 'admit' (default) fsyncs the "
                        "records that change what a restart owes "
                        "(admissions + terminals) and lets progress marks "
                        "ride the kernel buffers, 'never' leaves all "
                        "durability to the OS (still crash-consistent — "
                        "torn tails truncate, never corrupt)")
    p.add_argument("--wal_max_mb", type=float, default=64.0,
                   help="WAL segment rotation size; sealed segments whose "
                        "every request is terminal are compacted "
                        "(deleted) automatically")
    _add_robustness_flags(p)
    _add_adapter_flags(p)
    _add_pressure_flags(p)
    _add_observability_flags(p)
    _add_sched_flags(p)
    _add_slo_flags(p)
    _add_autoscale_flags(p)
    # Demo driver: submit a prompt pickle at staggered times, write the
    # offline-contract outputs. Without it, requests are read as JSON lines
    # from stdin: {"prefix": ..., "suffixes": [...], "max_new_tokens": N}.
    p.add_argument("--prompt_pickle", type=str, default=None,
                   help="demo mode: submit this offline prompt pickle's "
                        "entries as staggered online requests, then write "
                        "--output_file like the batch path")
    p.add_argument("--output_file", type=str, default=None)
    p.add_argument("--stagger_ms", type=float, default=0.0,
                   help="demo mode: delay between submissions, so late "
                        "arrivals exercise mid-stream wave admission")
    return p


def serve_main(argv: list[str] | None = None, tokenizer=None) -> None:
    args = build_serve_parser().parse_args(argv)
    print(args, file=sys.stderr)
    if args.prompt_pickle and not args.output_file:
        raise SystemExit("--prompt_pickle (demo mode) requires --output_file")
    from flexible_llm_sharding_tpu.config import ServeConfig

    cfg = FrameworkConfig(
        model_path=args.model_path,
        layer_num_per_shard=args.layer_num_per_shard,
        storage_location=args.storage_location,
        dtype=args.dtype,
        block_size=args.block_size,
        bucket_multiple=args.bucket_multiple,
        prefetch_depth=args.prefetch_depth,
        max_token_len=args.max_token_len,
        use_pallas=args.use_pallas,
        decode_resident=args.decode_resident,
        io_retry_attempts=args.io_retry_attempts,
        io_retry_base_s=args.io_retry_base_s,
        io_retry_deadline_s=args.io_retry_deadline_s,
        verify_weights=args.verify_weights,
        host_cache_gb=args.host_cache_gb,
        kv_page_tokens=args.kv_page_tokens,
        kv_pool_gb=args.kv_pool_gb,
        kv_host_spill=args.kv_host_spill,
        hbm_pin_gb=args.hbm_pin_gb,
        readahead_threads=args.readahead_threads,
        score_sink_max_device=args.score_sink_max_device,
        trace=args.trace,
        trace_out=args.trace_out,
        journal_dir=args.journal_dir,
        journal_max_mb=args.journal_max_mb,
        incidents_dir=args.incidents_dir,
        incidents_max_mb=args.incidents_max_mb,
        incident_trigger=args.incident_trigger,
        incident_debounce_s=args.incident_debounce_s,
        incident_settle_s=args.incident_settle_s,
        faults=_fault_config_from_args(args),
        pressure=_pressure_config_from_args(args),
        adapters=_adapter_config_from_args(args),
    )
    serve_cfg = ServeConfig(
        queue_capacity=args.queue_capacity,
        max_wave_requests=args.max_wave_requests,
        max_active_requests=args.max_active_requests,
        default_max_new_tokens=args.max_new_tokens,
        default_deadline_s=args.deadline_s,
        stats_interval_s=args.stats_interval_s,
        watchdog_abort_s=args.watchdog_abort_s,
        metrics_port=args.metrics_port,
        replicas=args.replicas,
        router_phase_weight=args.router_phase_weight,
        router_depth_weight=args.router_depth_weight,
        router_health_poll_s=args.router_health_poll_s,
        router_drain_recoveries=args.router_drain_recoveries,
        max_request_tokens=args.max_request_tokens,
        speculative_k=args.speculative_k,
        draft_model_path=args.draft_model_path,
        spec_adaptive=args.spec_adaptive,
        spec_k_min=args.spec_k_min,
        spec_k_max=args.spec_k_max,
        spec_window=args.spec_window,
        spec_raise_threshold=args.spec_raise_threshold,
        spec_backoff_threshold=args.spec_backoff_threshold,
        spec_draft_budget=args.spec_draft_budget,
        wal_dir=args.wal_dir,
        wal_fsync=args.wal_fsync,
        wal_max_mb=args.wal_max_mb,
        sched=_sched_config_from_args(args),
        slo=_slo_config_from_args(args),
        autoscale=_autoscale_config_from_args(args),
    )
    if tokenizer is None:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(cfg.model_path)
        tokenizer.pad_token = tokenizer.eos_token

    import time

    from flexible_llm_sharding_tpu.serve import ReplicaFleet, ServeEngine

    from flexible_llm_sharding_tpu.serve.request import RequestStatus

    # --replicas > 1 swaps the single engine for the replica fleet
    # (serve/fleet.py) — same submit/drain/shutdown/stats surface, so the
    # demo and jsonl frontends below drive either interchangeably.
    if _serve_wants_fleet(serve_cfg):
        engine = ReplicaFleet(cfg, serve_cfg, tokenizer=tokenizer)
    else:
        engine = ServeEngine(cfg, serve_cfg, tokenizer=tokenizer)
    if engine.metrics_server is not None:
        print(
            f"metrics endpoint: http://{engine.metrics_server.host}:"
            f"{engine.metrics_server.port}/metrics",
            file=sys.stderr,
            flush=True,
        )

    # Crash-safe serving (docs/recovery.md): `wal` is None unless
    # --wal_dir is set. Replay runs at the top of whichever frontend
    # branch executes — every still-open request from the previous boot
    # is re-admitted BEFORE new traffic, so the oldest owed work reaches
    # the scheduler first.
    wal = getattr(engine, "_wal", None)

    def _replay_open(callback=None) -> None:
        if wal is not None:
            from flexible_llm_sharding_tpu.serve import recovery

            summary = recovery.replay(engine, wal, callback=callback)
            print(
                f"wal replay: {summary['replayed']} reopened, "
                f"{summary['skipped_terminal']} already terminal, "
                f"kv restored {summary['kv_restored']} "
                f"(failed {summary['kv_failed']})",
                file=sys.stderr,
                flush=True,
            )
        # Autoscaler interlock: the controller's first scale decision
        # waits until replay has re-admitted the owed work (idempotent;
        # a fleet without a controller no-ops).
        mark = getattr(engine, "mark_replay_complete", None)
        if mark is not None:
            mark()

    import signal as _signal

    def _on_sigterm(signum, frame):
        # Graceful restart contract: stop admission, let the in-flight
        # wave reach its sweep boundary, journal + spill, exit clean.
        # Queued and in-flight requests land back in the WAL and replay
        # on the next start.
        engine.shutdown_for_restart()
        raise SystemExit(143)

    try:
        _signal.signal(_signal.SIGTERM, _on_sigterm)
    except ValueError:
        # Embedded call from a non-main thread: signals are unavailable;
        # the host process owns shutdown sequencing.
        pass
    try:
        if args.prompt_pickle:
            _replay_open()
            with open(args.prompt_pickle, "rb") as f:
                prompts = pickle.load(f)
            requests = []
            for prefix, suffixes in prompts:
                # The offline contract is one score per prompt, so the demo
                # submitter BLOCKS on backpressure (retry until a queue
                # slot frees) instead of dropping rejected prompts — a
                # pickle larger than --queue_capacity must still fully
                # serve. An engine-fatal error breaks the retry loop; the
                # root cause surfaces at the gather below.
                while True:
                    req = engine.submit(prefix, tuple(suffixes))
                    if (
                        req.status is not RequestStatus.REJECTED
                        or engine.error is not None
                    ):
                        break
                    time.sleep(0.05)
                requests.append(req)
                if args.stagger_ms:
                    time.sleep(args.stagger_ms / 1000.0)
            results = [r.future.result() for r in requests]
            with open(args.output_file, "wb") as f:
                pickle.dump([r.scores for r in results], f)
            with open(_updated_path(args.prompt_pickle), "wb") as f:
                pickle.dump([r.updated for r in results], f)
        else:
            # JSONL request stream on stdin; one JSON response line per
            # completion on stdout (scores stay server-side — tokens and
            # text travel).
            import threading

            out_lock = threading.Lock()

            def reply(req) -> None:
                try:
                    res = req.future.result(timeout=0)
                    line = {
                        "id": req.request_id,
                        "status": req.status.value,
                        "updated_suffixes": list(res.updated[1]),
                        "tokens": res.tokens.tolist(),
                        "ttft_s": round(res.ttft_s, 4),
                        "latency_s": round(res.latency_s, 4),
                    }
                except Exception as e:  # rejected/expired/failed
                    line = {
                        "id": req.request_id,
                        "status": req.status.value,
                        "error": str(e),
                    }
                # The caller's own id: request_id is per-process, so this
                # is the one identity that survives a restart — a client
                # deduping replayed (re-emitted) results keys on it.
                if req.client_id is not None:
                    line["client_id"] = req.client_id
                with out_lock:
                    print(json.dumps(line), flush=True)

            _replay_open(callback=reply)
            for line_no, raw in enumerate(sys.stdin, 1):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    d = json.loads(raw)
                    engine.submit(
                        d["prefix"],
                        tuple(d.get("suffixes") or ("",)),
                        max_new_tokens=d.get("max_new_tokens"),
                        deadline_s=d.get("deadline_s"),
                        callback=reply,
                        # Multi-tenant scheduling (serve/sched): an
                        # unknown slo_class raises typed and lands in the
                        # bad-request reply below, never a silent default.
                        slo_class=d.get("slo_class"),
                        tenant_id=d.get("tenant_id"),
                        # Multi-tenant LoRA (adapters/): an unknown or
                        # corrupt adapter fails ONLY this request, typed,
                        # at wave assembly — never the server.
                        adapter_id=d.get("adapter_id"),
                        # WAL identity: the caller's "id" rides into the
                        # admission record so replayed results remain
                        # attributable across restarts.
                        client_id=d.get("id"),
                    )
                except Exception as e:
                    # One malformed line must not take the server down for
                    # every other client: reject-with-reason, keep serving
                    # (backpressure/deadline rejects already flow through
                    # the callback; this covers parse/validation errors).
                    with out_lock:
                        print(
                            json.dumps(
                                {
                                    "line": line_no,
                                    "status": "rejected",
                                    "error": f"bad request line: {e!r}",
                                }
                            ),
                            flush=True,
                        )
    except BaseException as e:
        if engine.error is not None and not isinstance(e, SystemExit):
            # A fatal engine error cancels queued requests, so the gather
            # raises the secondary ServeClosed — name the ROOT cause
            # instead of the symptom.
            raise SystemExit(
                f"serve engine failed: {engine.error!r}"
            ) from e
        raise
    finally:
        engine.shutdown(drain=True)
        # Trace export in the FINALLY: a run that died is exactly the run
        # whose timeline (wave aborts, recoveries, watchdog stalls) the
        # operator needs — exiting through the error paths above without
        # writing it would discard the one diagnostic artifact tracing
        # exists to produce.
        if cfg.trace:
            from flexible_llm_sharding_tpu.obs import trace as obs_trace

            path = obs_trace.write_configured()
            if path:
                print(
                    f"trace written -> {path} (analyze: `trace-report "
                    f"--trace {path}`, or load in Perfetto)",
                    file=sys.stderr,
                )
    if engine.error is not None:
        raise SystemExit(f"serve engine failed: {engine.error!r}")
    print(json.dumps(engine.stats()), file=sys.stderr)


def build_verify_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="flexible-llm-sharding-tpu verify",
        description="Offline integrity audit: recompute every checksum in "
        "a prepared model dir (against integrity.json) and/or a spill dir "
        "(against the per-.npy sidecars). Prints a per-file report and "
        "exits nonzero on any problem — including manifest/dir structural "
        "drift, which the tolerant load path deliberately does not fail on.",
    )
    p.add_argument("--model_path", type=str, default=None,
                   help="prepared per-layer checkpoint dir to audit")
    p.add_argument("--spill_dir", type=str, default=None,
                   help="activation spill dir (--disk_folder of a run) to "
                        "audit")
    p.add_argument("--adapter_dir", type=str, default=None,
                   help="LoRA adapter registry root (the serve flag of "
                        "the same name) to audit: every adapter's delta "
                        "safetensors recomputed against its integrity "
                        "manifest, plan <-> file structural drift "
                        "reported (adapter_mismatch / plan_missing_file "
                        "/ corrupt_plan)")
    p.add_argument("--hbm_pin_gb", type=str, default=None,
                   help="dry-run the device residency planner at this HBM "
                        "budget (GB, or 'auto' for the local chip's "
                        "measured free HBM minus headroom): reports which "
                        "layers the budget would pin and the per-sweep "
                        "stream bytes saved; requires --model_path. Audit "
                        "only — nothing is loaded or pinned")
    p.add_argument("--json", action="store_true",
                   help="emit the full structured report as one JSON object "
                        "on stdout instead of human-readable lines")
    return p


def verify_main(argv: list[str] | None = None) -> None:
    args = build_verify_parser().parse_args(argv)
    if not args.model_path and not args.spill_dir and not args.adapter_dir:
        raise SystemExit(
            "verify: give --model_path, --spill_dir and/or --adapter_dir"
        )
    if args.hbm_pin_gb is not None and not args.model_path:
        raise SystemExit("verify: --hbm_pin_gb requires --model_path")
    from flexible_llm_sharding_tpu.integrity.verify import (
        format_report,
        verify_adapter_dir,
        verify_model_dir,
        verify_spill_dir,
    )

    reports = []
    if args.model_path:
        reports.append(verify_model_dir(args.model_path))
    if args.spill_dir:
        reports.append(verify_spill_dir(args.spill_dir))
    if args.adapter_dir:
        reports.append(verify_adapter_dir(args.adapter_dir))
    residency_plan = None
    if args.hbm_pin_gb is not None:
        from flexible_llm_sharding_tpu.runtime.residency import (
            auto_pin_budget_bytes,
            plan_report,
        )

        if args.hbm_pin_gb.lower() == "auto":
            budget = auto_pin_budget_bytes()
        else:
            try:
                gb = float(args.hbm_pin_gb)
            except ValueError:
                raise SystemExit(
                    "verify: --hbm_pin_gb must be a GB number or 'auto', "
                    f"got {args.hbm_pin_gb!r}"
                )
            if gb < 0:
                raise SystemExit("verify: --hbm_pin_gb must be >= 0")
            budget = int(gb * 1e9)
        residency_plan = plan_report(args.model_path, budget)
    if args.json:
        out = {"reports": reports}
        if residency_plan is not None:
            out["residency_plan"] = residency_plan
        print(json.dumps(out))
    else:
        for r in reports:
            print(format_report(r))
        if residency_plan is not None:
            rp = residency_plan
            print(
                f"residency plan @ {rp['budget_gb']} GB: pins "
                f"{rp['pinned_layers']}/{rp['total_layers']} layers, "
                f"{rp['pinned_bytes'] / 1e9:.3f} GB "
                f"({rp['pinned_fraction']:.1%} of streamed bytes) — saves "
                f"{rp['stream_bytes_saved_per_sweep'] / 1e9:.3f} GB of "
                "host->HBM traffic per sweep"
            )
            for entry in rp["pinned"]:
                print(f"  pin {entry['layer']}  {entry['bytes']} bytes")
    if not all(r["ok"] for r in reports):
        raise SystemExit(2)


def build_plan_precision_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="flexible-llm-sharding-tpu plan-precision",
        description="Mixed-precision calibration (docs/precision.md): "
        "probe per-layer quality sensitivity on a calibration batch "
        "(one-layer-at-a-time quantization vs the bf16 oracle), plan an "
        "int4/int8/bf16 dtype per layer under a bytes-per-sweep budget "
        "OR an end-to-end divergence cap, and emit the serializable "
        "PrecisionPlan (optionally materializing the mixed checkpoint).",
    )
    p.add_argument("--model_path", type=str, required=True,
                   help="FLOAT native per-layer checkpoint dir (the "
                        "original precision — quantized dirs are "
                        "rejected, requantize_native's rule)")
    p.add_argument("--calib_pickle", type=str, required=True,
                   help="calibration prompts pickle, the batch CLI's "
                        "[(prefix, (suffixes...)), ...] format")
    p.add_argument("--calib_limit", type=int, default=8,
                   help="use at most this many calibration prompts (the "
                        "probe runs one forward per layer per candidate "
                        "dtype per row)")
    p.add_argument("--bytes_budget_gb", type=float, default=None,
                   help="plan mode 1: fit the sweep under this many GB "
                        "of streamed weight bytes, minimizing divergence")
    p.add_argument("--divergence_cap", type=float, default=None,
                   help="plan mode 2: minimize streamed bytes subject to "
                        "this cap on calibration next-token KL vs the "
                        "bf16 oracle")
    p.add_argument("--out", type=str, default=None,
                   help="write the plan JSON here (default: print only)")
    p.add_argument("--apply", type=str, default=None,
                   help="also materialize the mixed checkpoint into this "
                        "dir (requantize_native(plan=...); embeds the "
                        "plan + per-layer dtype manifest)")
    p.add_argument("--json", action="store_true",
                   help="emit the plan as JSON on stdout")
    return p


def plan_precision_main(argv: list[str] | None = None, tokenizer=None) -> None:
    args = build_plan_precision_parser().parse_args(argv)
    if (args.bytes_budget_gb is None) == (args.divergence_cap is None):
        raise SystemExit(
            "plan-precision: give exactly one of --bytes_budget_gb / "
            "--divergence_cap"
        )
    from flexible_llm_sharding_tpu.runtime.precisionplan import build_plan
    from flexible_llm_sharding_tpu.utils.checkpoint import requantize_native

    with open(args.calib_pickle, "rb") as f:
        prompts = pickle.load(f)
    prompts = prompts[: max(1, args.calib_limit)]
    if tokenizer is None:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(args.model_path)
    plan = build_plan(
        args.model_path,
        prompts,
        tokenizer,
        bytes_budget=(
            int(args.bytes_budget_gb * 1e9)
            if args.bytes_budget_gb is not None
            else None
        ),
        divergence_cap=args.divergence_cap,
    )
    if args.out:
        plan.write(args.out)
    if args.json:
        print(json.dumps(plan.to_json()))
    else:
        counts = plan.counts()
        print(
            f"plan: {counts['bf16']} bf16 / {counts['int8']} int8 / "
            f"{counts['int4']} int4 layers — "
            f"{plan.est_bytes / 1e9:.3f} GB/sweep vs "
            f"{plan.baseline_bytes / 1e9:.3f} GB uniform bf16 "
            f"({plan.bytes_saved_frac:.1%} saved); measured divergence "
            f"{plan.measured_divergence:.3e} (declared cap "
            f"{plan.divergence_cap:.3e})"
        )
        for name, dt in plan.layers:
            print(f"  {dt:>5}  {name}")
    if args.apply:
        done = requantize_native(args.model_path, args.apply, plan=plan)
        print(
            f"materialized {len(done)} mixed-precision layers -> "
            f"{args.apply}",
            file=sys.stderr,
        )


def build_prepare_adapter_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="flexible-llm-sharding-tpu prepare-adapter",
        description="Convert a HF PEFT LoRA checkpoint dir "
        "(adapter_config.json + adapter_model.safetensors) into the "
        "serving registry layout under --adapter_dir: per-decoder-layer "
        "delta safetensors, an adapter_plan.json (per-layer ranks, "
        "alpha, hidden size), and an integrity manifest so `verify` can "
        "audit it and corrupt deltas raise typed at serve time. "
        "Per-module lora_alpha/r is pre-folded into the stored B "
        "factors (apply scale exactly 1.0); v1 converts square target "
        "modules only (docs/adapters.md).",
    )
    p.add_argument("--peft_dir", type=str, required=True,
                   help="HF PEFT checkpoint dir to convert (must hold "
                        "adapter_model.safetensors — torch-pickle .bin "
                        "checkpoints are rejected typed)")
    p.add_argument("--adapter_dir", type=str, required=True,
                   help="registry root to write into (the serve flag of "
                        "the same name); the adapter lands at "
                        "<adapter_dir>/<name>")
    p.add_argument("--name", type=str, required=True,
                   help="adapter name — the adapter_id serving requests "
                        "carry")
    p.add_argument("--json", action="store_true",
                   help="emit the written plan as JSON on stdout")
    return p


def prepare_adapter_main(argv: list[str] | None = None) -> None:
    args = build_prepare_adapter_parser().parse_args(argv)
    from flexible_llm_sharding_tpu.adapters.registry import (
        AdapterPlan,
        convert_peft_checkpoint,
    )

    try:
        adir = convert_peft_checkpoint(
            args.peft_dir, args.adapter_dir, args.name
        )
    except ValueError as e:
        raise SystemExit(f"prepare-adapter: {e}")
    plan = AdapterPlan.load(adir)
    if args.json:
        print(json.dumps(plan.to_json()))
    else:
        ranks = plan.ranks
        print(
            f"adapter {plan.name!r} -> {adir}: {len(plan.layers)} layers, "
            f"rank {plan.rank} (alpha {plan.alpha:g}, scale "
            f"{plan.scale:g}), hidden {plan.hidden_size}, "
            f"{plan.nbytes() / 1e6:.2f} MB of deltas"
        )
        for lname, _ in plan.layers:
            print(f"  r={ranks[lname]:<3d} {lname}")
        print(
            f"serve with: --adapter_dir {args.adapter_dir} ; requests "
            f'carry {{"adapter_id": "{plan.name}"}}'
        )


def build_incidents_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="flexible-llm-sharding-tpu incidents",
        description="Inspect flight-recorder incident bundles "
        "(--incidents_dir; docs/incidents.md): list the bundles in a "
        "directory, show one bundle's manifest, or analyze one into a "
        "human timeline (journal events + correlation ids + the "
        "embedded trace's report).",
    )
    p.add_argument("action", choices=("list", "show", "analyze"),
                   help="list bundles in --dir; show one bundle's "
                        "manifest; analyze one bundle into a timeline")
    p.add_argument("bundle", nargs="?", default=None,
                   help="bundle directory (show/analyze)")
    p.add_argument("--dir", type=str, default="incidents",
                   help="incidents directory to list (default: "
                        "./incidents)")
    p.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON on stdout")
    return p


def incidents_main(argv: list[str] | None = None) -> None:
    args = build_incidents_parser().parse_args(argv)
    from flexible_llm_sharding_tpu.obs.report import (
        analyze_bundle,
        format_incident,
        journal_tail_len,
        load_manifest,
    )

    if args.action == "list":
        try:
            names = sorted(os.listdir(args.dir))
        except OSError as e:
            raise SystemExit(f"incidents: cannot list {args.dir}: {e}")
        rows = []
        for name in names:
            path = os.path.join(args.dir, name)
            if not name.startswith("incident-") or not os.path.isdir(path):
                continue
            try:
                # Manifest + tail line count only: listing a full
                # incidents dir must not parse every bundle's multi-MB
                # trace export.
                manifest = load_manifest(path)
            except ValueError:
                continue  # half-written/foreign dir: skip, never crash
            trig = manifest.get("trigger", {})
            rows.append(
                {
                    "bundle": name,
                    "captured_at": manifest.get("captured_at"),
                    "trigger": trig.get("kind"),
                    "severity": trig.get("severity"),
                    "journal_events": journal_tail_len(path),
                }
            )
        if args.json:
            print(json.dumps(rows))
        elif not rows:
            print(f"no incident bundles under {args.dir}")
        else:
            for r in rows:
                print(
                    f"{r['bundle']}  {r['captured_at']}  "
                    f"trigger={r['trigger']} ({r['severity']})  "
                    f"journal_events={r['journal_events']}"
                )
        return None
    if not args.bundle:
        raise SystemExit(f"incidents {args.action}: give a bundle dir")
    try:
        if args.action == "show":
            manifest = load_manifest(args.bundle)
            print(json.dumps(manifest, indent=None if args.json else 1))
            return None
        report = analyze_bundle(args.bundle)
    except ValueError as e:
        raise SystemExit(f"incidents: {e}")
    if args.json:
        print(json.dumps(report))
    else:
        print(format_incident(report))
    return None


def main(argv: list[str] | None = None, tokenizer=None) -> None:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        return serve_main(argv[1:], tokenizer=tokenizer)
    if argv and argv[0] == "verify":
        return verify_main(argv[1:])
    if argv and argv[0] == "plan-precision":
        # Mixed-precision calibration planner (docs/precision.md).
        return plan_precision_main(argv[1:], tokenizer=tokenizer)
    if argv and argv[0] == "prepare-adapter":
        # HF PEFT LoRA checkpoint -> serving registry layout
        # (adapters/registry.py, docs/adapters.md).
        return prepare_adapter_main(argv[1:])
    if argv and argv[0] == "check":
        # flscheck: the project-invariant static analyzer (docs/analysis.md).
        from flexible_llm_sharding_tpu.analysis import main as check_main

        rc = check_main(argv[1:])
        if rc:
            raise SystemExit(rc)
        return None
    if argv and argv[0] == "incidents":
        # Flight-recorder bundle inspector (obs/report.py,
        # docs/incidents.md): list / show / analyze.
        return incidents_main(argv[1:])
    if argv and argv[0] == "trace-report":
        # Trace analyzer (obs/report.py): link utilization, overlap
        # efficiency, sweep breakdown, TTFT/token-latency quantiles from
        # a --trace recording.
        from flexible_llm_sharding_tpu.obs.report import main as report_main

        rc = report_main(argv[1:])
        if rc:
            raise SystemExit(rc)
        return None
    args = build_parser().parse_args(argv)
    print(args, file=sys.stderr)
    if (args.top_k or args.top_p) and args.temperature <= 0:
        # Friendly form of the FrameworkConfig validation: silent no-op
        # filters would masquerade as sampling.
        raise SystemExit("--top_k/--top_p require --temperature > 0")
    if args.decode_resident == "on" and not args.kv_cache:
        # Same silent-no-op defence: the flag only drives the KV-decode
        # path; without --kv_cache weights would quietly re-stream.
        raise SystemExit("--decode_resident on requires --kv_cache true")
    if args.speculative_k:
        if not args.kv_cache:
            raise SystemExit("--speculative_k requires --kv_cache true")
        if args.data_parallel:
            raise SystemExit(
                "--speculative_k does not compose with --data_parallel "
                "(the broadcast source's round count is fixed up front)"
            )
        if args.long_context:
            raise SystemExit(
                "--speculative_k is not supported with --long_context yet"
            )
    cfg = config_from_args(args)

    if args.coordinator_address is not None:
        from flexible_llm_sharding_tpu.parallel.sharding import initialize_multihost

        idx = initialize_multihost(
            args.coordinator_address, args.num_processes, args.process_id
        )
        print(f"joined cluster as process {idx}", file=sys.stderr)
    elif args.num_processes is not None or args.process_id is not None:
        # Without a coordinator every host would silently run the full
        # workload as process 0 and race on the output files.
        raise SystemExit(
            "--num_processes/--process_id require --coordinator_address"
        )

    if cfg.storage_location == "disk":
        os.makedirs(cfg.disk_folder, exist_ok=True)

    with open(args.prompt_pickle, "rb") as f:
        prompts = pickle.load(f)

    import jax

    if jax.process_count() > 1:
        # Multi-host: each process scores its own contiguous prompt slice
        # (array_split semantics, matching DP) on its LOCAL chips, and writes
        # rank-suffixed output files — otherwise every host would run the
        # full workload and race on the same pickles.
        from flexible_llm_sharding_tpu.parallel.planner import split_prompts_dp

        rank = jax.process_index()
        lo, hi = split_prompts_dp(len(prompts), jax.process_count())[rank]
        prompts = prompts[lo:hi]
        output_file = f"{args.output_file}.rank{rank}"
        updated_file = _updated_path(args.prompt_pickle, rank)
        print(
            f"process {rank}: prompts [{lo}:{hi}) -> {output_file}",
            file=sys.stderr,
        )
    else:
        output_file = args.output_file
        updated_file = _updated_path(args.prompt_pickle)

    from flexible_llm_sharding_tpu.runtime.generation import generation_loop
    from flexible_llm_sharding_tpu.runtime.orchestration import (
        pick_devices,
        run_prompts,
    )

    if tokenizer is None:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(cfg.model_path)
        tokenizer.pad_token = tokenizer.eos_token

    import time

    from flexible_llm_sharding_tpu.utils.metrics import (
        LiveArrayPeakSampler,
        peak_hbm_gb,
        profiler_trace,
        throughput,
    )

    from flexible_llm_sharding_tpu.runtime.tokenization import count_tokens

    # tokens_processed counts every real prefix/suffix token each full-model
    # pass runs — the same accounting bench.py and BASELINE.md use (the
    # reference's stats count only generated tokens, which understates the
    # work by orders of magnitude for scoring workloads).
    tokens_processed = 0

    from flexible_llm_sharding_tpu.runtime.executor import (
        process_streamed_bytes,
        reset_process_streamed_bytes,
    )
    from flexible_llm_sharding_tpu.runtime.orchestration import (
        LAST_DP_RANK_STATS,
    )

    # Fresh per-run accumulators (a library caller may run cli.main twice
    # in one process).
    LAST_DP_RANK_STATS.clear()
    reset_process_streamed_bytes()

    # Brownout controller (--pressure): started HERE for the offline
    # path — the monitor thread, ladder, and fls_pressure_* export are
    # process-wide singletons that serve engines start themselves, but a
    # batch run has no engine, and without this call the flag would
    # parse and thread yet never act (the silent-no-op class KNOB-SYNC
    # can't see because the args ARE read).
    from flexible_llm_sharding_tpu.runtime import pressure as _pressure

    _pressure.controller_for(cfg)
    # Flight recorder (--journal_dir/--incidents_dir): armed here for the
    # offline path — serve engines arm it themselves, but a batch run's
    # failure paths (quarantines, heals, pressure events) must journal
    # and bundle too.
    from flexible_llm_sharding_tpu.obs import incident as _incident

    _incident.ensure_configured(cfg)

    t0 = time.perf_counter()
    # The sampler is the peak-HBM fallback for devices whose memory_stats()
    # is unavailable (e.g. TPU through the axon tunnel).
    hbm_sampler = LiveArrayPeakSampler()
    with profiler_trace(cfg.profile_dir or None), hbm_sampler:
        if args.kv_cache:
            # Sampling composes (cfg carries temperature/top_k/top_p/seed);
            # --long_context composes: run_decode routes over-length
            # prefixes to the sp-mesh LongContextDecoder.
            from flexible_llm_sharding_tpu.runtime.orchestration import run_decode

            # Multi-chip: --data_parallel true splits prompts across chips;
            # default is the interleaved MP pipeline with per-stage KV.
            output_scores, updated, tokens_processed = run_decode(
                cfg, prompts, tokenizer=tokenizer
            )
        else:

            # Long-context mode actually processes prefixes up to
            # n_chips * max_token_len; count with the same cap.
            count_cap = cfg.max_token_len * (
                len(pick_devices(cfg)) if cfg.long_context else 1
            )

            def score_fn(ps):
                nonlocal tokens_processed
                tokens_processed += count_tokens(tokenizer, ps, count_cap)
                return run_prompts(cfg, ps, tokenizer=tokenizer)

            from flexible_llm_sharding_tpu.config import LlamaConfig

            output_scores, updated = generation_loop(
                score_fn,
                prompts,
                cfg.num_gen_token,
                tokenizer,
                temperature=args.temperature,
                seed=args.seed,
                top_k=args.top_k,
                top_p=args.top_p,
                model_cfg=LlamaConfig.from_pretrained(cfg.model_path),
                max_token_len=cfg.max_token_len,
            )
    wall = time.perf_counter() - t0

    # Reference file contract (/root/reference/main.py:92-98).
    with open(updated_file, "wb") as f:
        pickle.dump(updated, f)
    with open(output_file, "wb") as f:
        pickle.dump(output_scores, f)
    # Final stats line — the reference prints its per-device weight-load time
    # here (/root/reference/utils.py:304); ours adds throughput and peak HBM.
    gen_tokens = sum(s.shape[0] for s in output_scores) * cfg.num_gen_token
    stats = {
        "prompts": len(prompts),
        "num_gen_token": cfg.num_gen_token,
        "wall_s": round(wall, 3),
        "generated_tokens": gen_tokens,
        "tokens_processed": tokens_processed,
        **throughput(tokens_processed, wall, chips=len(pick_devices(cfg))),
    }
    peak = peak_hbm_gb()
    if peak is not None:
        stats["peak_hbm_gb"] = round(peak, 3)
        stats["peak_hbm_source"] = "allocator"  # device.memory_stats() peak
    elif hbm_sampler.peak_bytes:
        stats["peak_hbm_gb"] = round(hbm_sampler.peak_gb, 3)
        stats["peak_hbm_source"] = "live_arrays"  # excludes XLA scratch
        if len(pick_devices(cfg)) > 1:
            # live_arrays sums across every local chip; on multi-chip runs
            # this is the process-wide total, not the per-chip peak.
            stats["peak_hbm_scope"] = "process"
    # Total host shard bytes built for upload this process — for a
    # single-chip stream this is the model bytes that crossed the host->HBM
    # link (x num_batch passes), the scale artifact's "the whole model
    # really streamed through" witness.
    from flexible_llm_sharding_tpu.runtime.residency import process_tier

    tier = process_tier()
    if tier is not None:
        rs = tier.stats()
        # HBM accounting honesty: the pin tier is device-resident for the
        # whole run. The allocator peak already includes it; the
        # live-arrays fallback samples it too, but on a backend where
        # neither produced a figure the tier's own bytes become the floor
        # — the low-memory claim can never silently exclude the pins.
        stats["pinned_bytes"] = int(rs["pinned_bytes"])
        if rs["stream_bytes_saved"]:
            stats["stream_bytes_saved"] = int(rs["stream_bytes_saved"])
        if "peak_hbm_gb" not in stats and rs["pinned_bytes"]:
            # Per-chip figure: the heaviest single placement target, NOT
            # the process-wide sum (a 4-stage pipeline pins on 4 chips;
            # the per-chip peak is one stage's bytes, not all four).
            stats["peak_hbm_gb"] = round(
                tier.max_pinned_device_bytes() / 1e9, 3
            )
            stats["peak_hbm_source"] = "pinned_floor"
    sb = process_streamed_bytes()
    if sb:
        stats["streamed_bytes"] = sb
        # These are HOST shard builds. Single chip: equals host->HBM link
        # traffic. DP broadcast: each host build uploads to every active
        # rank, so link traffic is ~n_ranks x this (the read-once design's
        # point); scope the number so artifacts can't misstate it.
        stats["streamed_bytes_scope"] = "host_loads"
        if cfg.data_parallel and len(pick_devices(cfg)) > 1:
            stats["streamed_bytes_note"] = (
                "broadcast: link traffic ~= n_ranks x host_loads"
            )
    # Host memory: VmHWM (peak RSS — an UPPER bound that includes mmapped
    # checkpoint pages the loader faulted in, so it can approach model size
    # on an unpressured host) plus the sampled peak ANON RSS, the process's
    # own buffers — the honest witness of the streaming host-memory bound.
    from flexible_llm_sharding_tpu.utils.metrics import host_rss_gb

    rss = host_rss_gb()
    if "peak" in rss:
        stats["peak_host_rss_gb"] = round(rss["peak"], 3)
        stats["peak_host_rss_note"] = "includes mmapped checkpoint pages"
    if hbm_sampler.peak_anon_bytes:
        stats["peak_host_anon_gb"] = round(
            hbm_sampler.peak_anon_bytes / 1e9, 3
        )
    if LAST_DP_RANK_STATS:
        stats["dp_ranks"] = {
            str(r): {
                k: int(v) if k == "prompts" else round(v, 3)
                for k, v in s.items()
            }
            for r, s in sorted(LAST_DP_RANK_STATS.items())
        }
    print(json.dumps(stats), file=sys.stderr)
    if args.metrics_out:
        # One-shot machine-readable dump: the metrics registry every
        # subsystem registered into (executor stats, stream counters,
        # host cache, residency tier, tracer) plus the final stats line —
        # the scrapeable form of everything printed above.
        from flexible_llm_sharding_tpu.obs.registry import REGISTRY

        with open(args.metrics_out, "w") as f:
            json.dump({"stats": stats, "metrics": REGISTRY.collect()}, f,
                      indent=1)
        print(f"metrics written -> {args.metrics_out}", file=sys.stderr)
    if cfg.trace:
        from flexible_llm_sharding_tpu.obs import trace as obs_trace

        path = obs_trace.write_configured()
        if path:
            print(
                f"trace written -> {path} (analyze: `trace-report --trace "
                f"{path}`, or load in Perfetto)",
                file=sys.stderr,
            )


if __name__ == "__main__":
    main()
