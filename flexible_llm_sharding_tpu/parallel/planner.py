"""Shard planning: how the layer list is cut into shards and assigned to devices.

Reproduces the reference's planning math exactly
(``/root/reference/utils.py:144-153``):

- The "layer list" is the FULL execution list — ``model.embed_tokens``,
  ``model.layers.{i}``, ``model.norm``, ``lm_head`` — not just decoder layers.
- **DP** (each device streams the whole model over its own prompt slice):
  ``num_shards = ceil(n_layers / layer_num_per_shard)`` contiguous pieces via
  ``np.array_split`` (first ``n % num_shards`` pieces get one extra layer).
- **MP** (interleaved pipeline): shard count is rounded UP to a multiple of the
  device count, then device ``k`` takes shards ``all_shards[k::num_devices]``
  (round-robin / interleaved stages, cf. the reference's
  ``multigpu_flexibility.png``).

Prompt splitting for DP mode matches ``np.array_split(prompts, num_devices)``
(``/root/reference/main.py:70``).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """One device's work: a list of shards, each a tuple of global layer indices."""

    shards: tuple[tuple[int, ...], ...]
    n_layers: int  # total layers in the model's execution list
    device_rank: int = 0
    num_devices: int = 1

    @property
    def num_local_layers(self) -> int:
        return sum(len(s) for s in self.shards)

    def owns_layer(self, layer_idx: int) -> bool:
        return any(layer_idx in s for s in self.shards)


def _array_split_sizes(n: int, parts: int) -> list[int]:
    """Sizes produced by ``np.array_split(np.arange(n), parts)``."""
    base, extra = divmod(n, parts)
    return [base + 1] * extra + [base] * (parts - extra)


def _contiguous_shards(n_layers: int, num_shards: int) -> list[tuple[int, ...]]:
    out, start = [], 0
    for size in _array_split_sizes(n_layers, num_shards):
        out.append(tuple(range(start, start + size)))
        start += size
    return out


def plan_shards_dp(
    n_layers: int,
    layer_num_per_shard: int,
    device_rank: int = 0,
    num_devices: int = 1,
) -> ShardPlan:
    """DP / single-device plan: contiguous shards, all streamed by this device
    (``/root/reference/utils.py:145-146``). ``device_rank``/``num_devices``
    identify the device within a DP group (used e.g. to tag per-rank disk
    activation files, ``/root/reference/utils.py:172``)."""
    num_shards = math.ceil(n_layers / layer_num_per_shard)
    return ShardPlan(
        shards=tuple(_contiguous_shards(n_layers, num_shards)),
        n_layers=n_layers,
        device_rank=device_rank,
        num_devices=num_devices,
    )


def _mp_num_shards(n_layers: int, layer_num_per_shard: int, num_devices: int) -> int:
    """MP shard count: rounded up to a multiple of ``num_devices`` so every
    device gets the same number of stages (``/root/reference/utils.py:151``)."""
    return (
        math.ceil(math.ceil(n_layers / layer_num_per_shard) / num_devices)
        * num_devices
    )


def plan_shards_mp(
    n_layers: int, layer_num_per_shard: int, device_rank: int, num_devices: int
) -> ShardPlan:
    """MP plan for one device: round-robin interleaved stages
    (``/root/reference/utils.py:150-153``)."""
    num_shards = _mp_num_shards(n_layers, layer_num_per_shard, num_devices)
    all_shards = _contiguous_shards(n_layers, num_shards)
    return ShardPlan(
        shards=tuple(all_shards[device_rank::num_devices]),
        n_layers=n_layers,
        device_rank=device_rank,
        num_devices=num_devices,
    )


def global_stage_order(n_layers: int, layer_num_per_shard: int, num_devices: int):
    """All MP stages in execution order as (stage_idx, device_rank, layer_tuple)."""
    num_shards = _mp_num_shards(n_layers, layer_num_per_shard, num_devices)
    shards = _contiguous_shards(n_layers, num_shards)
    return [(i, i % num_devices, s) for i, s in enumerate(shards)]


def split_prompts_dp(num_prompts: int, num_devices: int) -> list[tuple[int, int]]:
    """[start, end) prompt ranges per device — ``np.array_split`` semantics
    (``/root/reference/main.py:70``)."""
    sizes = _array_split_sizes(num_prompts, num_devices)
    ranges, start = [], 0
    for size in sizes:
        ranges.append((start, start + size))
        start += size
    return ranges


def batch_ranges(num_prompts: int, num_batch: int) -> list[tuple[int, int]]:
    """The reference's batching rule (``/root/reference/main.py:19-20``):
    ``num_batch`` pieces of size ``num_prompts // num_batch`` with the remainder
    folded into the last piece."""
    ends = [num_prompts // num_batch * i for i in range(1, num_batch)] + [num_prompts]
    return list(zip([0] + ends[:-1], ends))


__all__ = [
    "ShardPlan",
    "plan_shards_dp",
    "plan_shards_mp",
    "global_stage_order",
    "split_prompts_dp",
    "batch_ranges",
]
