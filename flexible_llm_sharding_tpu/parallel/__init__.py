from flexible_llm_sharding_tpu.parallel.planner import (
    ShardPlan,
    plan_shards_dp,
    plan_shards_mp,
    split_prompts_dp,
)

__all__ = ["ShardPlan", "plan_shards_dp", "plan_shards_mp", "split_prompts_dp"]
