"""Device mesh + sharding rules: how Llama parameters and activations are laid
out over a TPU slice.

The reference has no tensor/sequence parallelism at all — each layer's full
weights go to exactly one device (``/root/reference/utils.py:128-130``) and
"communication" is host-staged tensor copies between Python threads
(``/root/reference/utils.py:166,193-195``). The TPU-native design replaces all
of that with one ``jax.sharding.Mesh`` plus ``NamedSharding`` annotations; XLA
inserts the ICI collectives (all-gather / reduce-scatter / psum) itself.

Mesh axes used across the framework:

- ``dp``  — data parallel: the prompt/batch axis (reference's ``--data_parallel``
  prompt split, ``/root/reference/main.py:67-70``).
- ``tp``  — tensor parallel: attention heads / MLP hidden sharding (Megatron
  layout: column-parallel in-projections, row-parallel out-projections so each
  layer needs exactly one psum, which XLA emits from the sharding annotations).
- ``sp``  — sequence/context parallel: long sequences sharded along length for
  norm/elementwise regions (XLA re-gathers where attention needs full keys).

Parameter layout reminder (models/llama.py): all linear kernels are stored
``[in, out]`` — the transpose of HF — so "column parallel" = shard the LAST
axis, "row parallel" = shard the FIRST axis.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flexible_llm_sharding_tpu.config import LlamaConfig

Params = dict[str, Any]


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join a multi-host (DCN) JAX cluster; returns this process's index.

    The reference tops out at the chips of one host (Python threads in one
    process, ``/root/reference/main.py:59-76``). On TPU pods the same mesh
    code spans hosts: call this once at startup on every host (args usually
    come from the TPU environment automatically), then build meshes from the
    GLOBAL device list — ``make_mesh`` already uses ``jax.devices()``, which
    is cluster-wide after initialization. Lay out mesh axes so the
    fastest-varying (tp/sp) axes stay within a host's ICI domain and only
    dp crosses DCN. No-op when the cluster is already initialized, or when
    auto-detection finds a single-process environment; an EXPLICIT
    coordinator address that fails to connect raises (a silent fallback to
    single-host would duplicate work and corrupt results).
    """
    if jax.distributed.is_initialized():
        return jax.process_index()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # An explicitly requested cluster must never silently degrade —
        # duplicate single-host runs would race on output files. In
        # auto-detect mode the ONLY benign RuntimeError is the called-after-
        # backend-init guard; a detected cluster that fails to join (e.g.
        # coordinator connect timeout) must raise too, so unmatched messages
        # re-raise — fail-loud if JAX ever rewords the guard.
        if coordinator_address is not None or "before" not in str(e).lower():
            raise
    except ValueError:
        # Auto-detection failed (no cluster env) — fine only if the caller
        # didn't explicitly ask for a cluster.
        if coordinator_address is not None:
            raise
    return jax.process_index()


def make_mesh(
    shape: dict[str, int] | None = None, devices: list | None = None
) -> Mesh:
    """Build a Mesh from axis-name -> size.

    ``shape=None`` gives a 1-D ``('dp',)`` mesh over all visible devices.
    Sizes must multiply to the device count (one axis may be -1 to infer).
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = {"dp": len(devices)}
    names = tuple(shape)
    sizes = list(shape.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(
            f"mesh shape {dict(zip(names, sizes))} needs {need} devices, "
            f"have {len(devices)}"
        )
    arr = np.asarray(devices[:need]).reshape(sizes)
    return Mesh(arr, names)


def layer_specs(
    tp: str | None = "tp",
    cfg: LlamaConfig | None = None,
    mlp_kind: str | None = None,
) -> Params:
    """PartitionSpecs for one decoder layer's params (Megatron TP layout).

    ``cfg`` adds entries for the bias vectors the model family carries
    (Qwen2 q/k/v, Llama attention_bias/mlp_bias): a column-parallel
    projection's bias shards with its output axis; a row-parallel
    projection's bias is replicated (added once, after the psum).

    ``mlp_kind`` overrides the MLP structure for models that interleave
    structurally different layers (llama4 / qwen3_moe dense interleave):
    ``"dense"`` or ``"moe"``; ``None`` derives it from ``cfg`` (MoE iff the
    config declares experts).
    """
    col = P(None, tp)  # [in, out] sharded on out
    row = P(tp, None)  # [in, out] sharded on in
    rep = P(None)
    bcol = P(tp)  # bias of a column-parallel projection
    if cfg is not None and cfg.kv_lora_rank:
        # MLA (deepseek_v3): the LoRA down-projections (q_a, kv_a) and
        # their norms are replicated — kv_a's output carries the shared
        # rope key every head needs, and both are tiny (rank x D). The
        # per-head up-projections (q_b / kv_b / dense wq) column-shard by
        # head like Megatron q/k/v; wo row-shards over the heads' values.
        attn: Params = {
            "kv_a": rep, "kv_a_norm": rep, "kv_b": col, "wo": row,
        }
        if cfg.q_lora_rank:
            attn |= {"q_a": rep, "q_a_norm": rep, "q_b": col}
        else:
            attn["wq"] = col
        if cfg.attention_in_bias:
            # Biases exist on the LoRA down-projections only (HF's dense
            # q_proj is bias=False unconditionally); they act on
            # replicated outputs.
            attn["bkv_a"] = rep
            if cfg.q_lora_rank:
                attn["bq_a"] = rep
    else:
        attn = {"wq": col, "wk": col, "wv": col, "wo": row}
    if mlp_kind is None:
        mlp_kind = "moe" if (cfg is not None and cfg.num_local_experts) else "dense"
    if mlp_kind == "moe":
        # Expert parallelism: the stacked [E, ...] expert arrays shard on the
        # expert axis — each chip computes its own experts for all tokens and
        # GSPMD inserts one psum for the routed combine (models/llama.py
        # _moe_mlp). Router stays replicated (it is [D, E], tiny).
        exp = P(tp, None, None)
        mlp: Params = {"router": rep, "gate": exp, "up": exp, "down": exp}
        if cfg is not None and cfg.model_type in ("llama4_text", "deepseek_v3"):
            # The always-on shared expert (llama4 / deepseek) is a plain
            # Megatron MLP alongside the expert-sharded routed stack; its
            # row-parallel down-projection folds into the same psum.
            mlp |= {"shared_gate": col, "shared_up": col, "shared_down": row}
        if cfg is not None and cfg.model_type == "deepseek_v3":
            mlp["correction_bias"] = rep  # [E] routing buffer, tiny
    else:
        mlp = {"gate": col, "up": col, "down": row}
    if cfg is not None:
        if cfg.attention_in_bias and not cfg.kv_lora_rank:
            attn |= {"bq": bcol, "bk": bcol, "bv": bcol}
        if cfg.attention_out_bias:
            attn["bo"] = rep
        if cfg.qk_norm:
            attn |= {"q_norm": rep, "k_norm": rep}  # [head_dim], tiny
        if cfg.mlp_bias and not cfg.num_local_experts:
            mlp |= {"bgate": bcol, "bup": bcol, "bdown": rep}
    out = {
        "input_layernorm": {"scale": rep},
        "post_attention_layernorm": {"scale": rep},
        "attn": attn,
        "mlp": mlp,
    }
    if cfg is not None and cfg.ffw_sandwich_norms:
        out["pre_feedforward_layernorm"] = {"scale": rep}
        out["post_feedforward_layernorm"] = {"scale": rep}
    return out


def param_specs(
    cfg: LlamaConfig,
    tp: str | None = "tp",
    stacked: bool = False,
    pp: str | None = None,
) -> Params:
    """PartitionSpec pytree matching ``llama.init_params`` layout.

    ``stacked=True`` means ``params['layers']`` is one pytree with a leading
    [num_layers] axis (the scan layout); ``pp`` optionally shards that layer
    axis across a pipeline mesh axis.
    """
    lspec = layer_specs(tp, cfg)
    if stacked:
        layers = jax.tree.map(
            lambda s: P(pp, *s), lspec, is_leaf=lambda x: isinstance(x, P)
        )
    else:
        layers = [lspec] * cfg.num_hidden_layers
    specs: Params = {
        "embed": {"embedding": P(None, tp)},  # [V, D] sharded on hidden
        "layers": layers,
        "norm": {"scale": P(None)},
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"kernel": P(None, tp)}  # [D, V] sharded on vocab
    return specs


def data_spec(dp: str | None = "dp", sp: str | None = None) -> P:
    """Token ids [B, L]: batch over dp, optionally sequence over sp."""
    return P(dp, sp)


class TpPlacement:
    """Weight/activation placement for tensor-parallel streaming inference.

    The reference never splits a layer across devices (each layer's full
    weights land on one GPU, ``/root/reference/utils.py:128-130``); on TPU the
    idiomatic alternative is Megatron-style sharding over a ``tp`` mesh axis:
    every streamed shard's matmuls are column/row-partitioned across the
    chips (``layer_specs``), activations stay replicated, and XLA inserts the
    ICI all-reduces where the row-parallel products need them. Per-chip
    weight HBM drops by the tp factor — multiplying with the streaming
    design's own layer_num_per_shard reduction — and the matmuls ride all
    chips' MXUs at once.

    Duck-types as the executor's ``device``: ``segment_target(kind)`` gives
    the ``jax.device_put`` target for one weight segment, ``act`` the target
    for activations. The jitted block programs need no changes — GSPMD
    partitions them from the argument shardings.
    """

    def __init__(self, devices: Sequence, cfg: LlamaConfig | None = None):
        if len(devices) < 2:
            raise ValueError("TpPlacement needs >= 2 devices")
        self.mesh = make_mesh({"tp": len(devices)}, list(devices))
        self.act = NamedSharding(self.mesh, P())

        def decoder_tree(mlp_kind: str | None):
            # Stacked-scan decoder pytrees carry a leading [k] layer axis.
            return jax.tree.map(
                lambda s: NamedSharding(self.mesh, P(None, *s)),
                layer_specs("tp", cfg, mlp_kind=mlp_kind),
                is_leaf=lambda x: isinstance(x, P),
            )

        self._decoder = decoder_tree(None)
        # Mixed dense/MoE stacks (llama4, qwen3_moe dense interleave) produce
        # structurally different "decoders" segments — the loader splits them
        # into homogeneous scan runs, and segment_target picks the matching
        # spec tree per run by the host structure.
        self._decoder_dense = (
            decoder_tree("dense")
            if cfg is not None and cfg.num_local_experts and cfg.moe_layer_pattern
            else self._decoder
        )
        self._by_kind = {
            "decoders": {
                "layers": self._decoder,
                "sliding": self.act
                if cfg is not None and cfg.layer_sliding is not None
                else None,
                "rope": self.act
                if cfg is not None and cfg.layer_rope is not None
                else None,
            },
            # Embed/norm are small and read row-wise per token id; replicate.
            "embed": self.act,
            "norm": self.act,
            # Head kernel [D, V] column-sharded: each chip scores a vocab
            # slice; the softmax's global max/sum become ICI all-reduces.
            "head": {"kernel": NamedSharding(self.mesh, P(None, "tp"))},
        }

    def segment_target(self, kind: str, host=None):
        """Sharding target for one streamed segment. ``host`` (the host-side
        pytree about to be device_put) disambiguates mixed dense/MoE models:
        a decoder run without a router takes the dense Megatron specs."""
        target = self._by_kind[kind]
        if (
            kind == "decoders"
            and host is not None
            and "router" not in host["layers"]["mlp"]
        ):
            target = dict(target, layers=self._decoder_dense)
        return target

    def check(self, cfg: LlamaConfig) -> None:
        check_tp_divisibility(cfg, self.mesh.shape["tp"])


def check_tp_divisibility(cfg: LlamaConfig, tp_size: int) -> None:
    """TP constraints — fail loudly before XLA produces a cryptic error."""
    if cfg.num_attention_heads % tp_size:
        raise ValueError(
            f"num_attention_heads={cfg.num_attention_heads} not divisible by tp={tp_size}"
        )
    if cfg.num_key_value_heads % tp_size:
        raise ValueError(
            f"num_key_value_heads={cfg.num_key_value_heads} not divisible by tp={tp_size}"
        )
    if cfg.num_local_experts:
        # MoE MLPs shard on the expert axis, not the hidden axis.
        if cfg.num_local_experts % tp_size:
            raise ValueError(
                f"num_local_experts={cfg.num_local_experts} not divisible by tp={tp_size}"
            )
        # Dense interleave layers (llama4 intermediate_size_mlp, qwen3_moe
        # mlp_only_layers) and llama4's shared expert shard on their own
        # hidden axis like any Megatron MLP.
        dense_f = cfg.intermediate_size_mlp or (
            cfg.intermediate_size if cfg.moe_layer_pattern else None
        )
        if (
            cfg.model_type in ("llama4_text", "deepseek_v3")
            and cfg.intermediate_size % tp_size
        ):
            raise ValueError(
                f"shared-expert intermediate_size={cfg.intermediate_size} "
                f"not divisible by tp={tp_size}"
            )
        if dense_f and dense_f % tp_size:
            raise ValueError(
                f"dense-layer intermediate size {dense_f} not divisible by tp={tp_size}"
            )
    elif cfg.intermediate_size % tp_size:
        raise ValueError(
            f"intermediate_size={cfg.intermediate_size} not divisible by tp={tp_size}"
        )


def tree_shardings(mesh: Mesh, specs: Params) -> Params:
    """PartitionSpec pytree -> NamedSharding pytree."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


def shard_params(params: Params, mesh: Mesh, specs: Params) -> Params:
    """device_put a (host or device) param pytree onto the mesh per specs."""
    return jax.device_put(params, tree_shardings(mesh, specs))


__all__ = [
    "initialize_multihost",
    "make_mesh",
    "param_specs",
    "layer_specs",
    "data_spec",
    "TpPlacement",
    "check_tp_divisibility",
    "tree_shardings",
    "shard_params",
]
