"""``flscheck`` — project-invariant static analysis for this repo.

The streaming architecture lives on a handful of concurrency and
configuration invariants (one producer thread feeding consumers through
process-wide caches and tiers; knobs threaded through two CLI parsers;
fault sites registered in ``config.FAULT_SITES``; counters exported to
stats). The last several PRs each burned review rounds on the *same*
recurring defect classes — this package machine-checks them per PR.

Entry points:

- ``python -m flexible_llm_sharding_tpu.cli check`` (the CI surface)
- ``python -m flexible_llm_sharding_tpu.analysis``
- ``scripts/flscheck``

See ``docs/analysis.md`` for the rule catalog, the pragma and baseline
workflow, and how to add a rule.
"""

from flexible_llm_sharding_tpu.analysis.core import (
    Finding,
    analyze_source,
    main,
    run,
)

__all__ = ["Finding", "analyze_source", "main", "run"]
