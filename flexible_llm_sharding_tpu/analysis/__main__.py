"""``python -m flexible_llm_sharding_tpu.analysis`` — the flscheck CLI."""

import sys

from flexible_llm_sharding_tpu.analysis.core import main

if __name__ == "__main__":
    sys.exit(main())
