"""The project-specific rule set (see docs/analysis.md for the catalog).

Every rule here encodes an invariant a past PR review caught by hand:

- LOCK-IO        blocking calls inside ``with <lock>:`` bodies
- GUARDED-BY     ``# guarded by: _lock`` attributes touched off-lock
- KNOB-SYNC      config fields vs the two CLI parsers vs construction
- SITE-REG       ``injector.fire("<site>")`` vs FAULT_SITES vs docs table
- EVENT-REG      ``emit("<kind>")`` vs obs/events.EVENT_KINDS vs docs table
- EXC-TAXONOMY   swallowing broad excepts / unchained re-raises in hot paths
- COUNTER-EXPORT counters incremented but absent from stats()/snapshot()
- DETERMINISM    unseeded randomness / wall-clock in faults+integrity
- QUANT-MANIFEST layer-file writers must record a manifest dtype entry
- HYGIENE        stray package dirs, missing __init__.py

Rules are AST-walks plus a little comment scanning — no imports of the
analyzed code, so a module with a broken import still gets checked.
"""

from __future__ import annotations

import ast
import os
import re

from flexible_llm_sharding_tpu.analysis.core import (
    FileInfo,
    Finding,
    ProjectContext,
    file_rule,
    project_rule,
)

# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> tuple[str, ...]:
    """('os', 'path', 'getsize') for an Attribute/Name chain, () otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


class _SymbolWalker(ast.NodeVisitor):
    """Base visitor that tracks the enclosing Class.method qualname."""

    def __init__(self) -> None:
        self.stack: list[str] = []

    @property
    def symbol(self) -> str:
        return ".".join(self.stack) or "module"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


# ---------------------------------------------------------------------------
# LOCK-IO
# ---------------------------------------------------------------------------

_LOCK_NAME_RE = re.compile(r"lock", re.IGNORECASE)

# Blocking calls by dotted suffix. The list is deliberately the *known*
# blocking families on this codebase's hot paths (stat/read/parse/upload/
# sleep), not a general-purpose I/O taxonomy — precision over recall.
_BLOCKING_SUFFIXES: tuple[tuple[str, ...], ...] = (
    ("os", "stat"),
    ("os", "fstat"),
    ("os", "lstat"),
    ("os", "listdir"),
    ("os", "scandir"),
    ("os", "path", "getsize"),
    ("os", "path", "exists"),
    ("os", "path", "getmtime"),
    ("np", "load"),
    ("numpy", "load"),
    ("np", "save"),
    ("numpy", "save"),
    ("time", "sleep"),
    ("jax", "device_put"),
    ("pickle", "load"),
    ("json", "load"),
)
_BLOCKING_NAME_CALLS = frozenset({"open", "safe_open", "load_file"})
# Known project wrappers that do blocking work inside (reads, checksums,
# retry ladders with backoff sleeps, device placement).
_BLOCKING_PROJECT_CALLS = frozenset(
    {
        "plan_residency",
        "layer_stream_bytes",
        "stat_guard",
        "_stat_key",
        "build_host_shard",
        "load_layer",
        "_load_one",
        "_place",
        "retry_call",
    }
)
_BLOCKING_METHODS = frozenset({"result"})  # future.result()


def _lock_name(item: ast.withitem) -> str | None:
    chain = _dotted(item.context_expr)
    if chain and _LOCK_NAME_RE.search(chain[-1]):
        return ".".join(chain)
    return None


def _blocking_call_label(call: ast.Call) -> str | None:
    chain = _dotted(call.func)
    if chain:
        if len(chain) == 1 and chain[0] in _BLOCKING_NAME_CALLS:
            return chain[0]
        if "safetensors" in chain:
            return ".".join(chain)
        for suffix in _BLOCKING_SUFFIXES:
            if chain[-len(suffix):] == suffix:
                return ".".join(chain)
        if chain[-1] in _BLOCKING_PROJECT_CALLS:
            return ".".join(chain)
        if len(chain) >= 2 and chain[-1] in _BLOCKING_METHODS:
            return ".".join(chain) + "()"
    return None


@file_rule(
    "LOCK-IO",
    "no blocking I/O (open/stat/load/device_put/.result()/sleep) inside "
    "`with <lock>:` bodies",
)
def lock_io(info: FileInfo, ctx: ProjectContext) -> list[Finding]:
    findings: list[Finding] = []
    # A disable pragma on the `with <lock>:` line (or the line above it)
    # exempts that whole critical section — one audited reason instead of
    # one pragma per call inside.
    block_pragma_lines = {
        p.line
        for p in info.pragmas
        if p.kind == "disable" and "LOCK-IO" in p.names
    }

    class V(_SymbolWalker):
        def __init__(self) -> None:
            super().__init__()
            self.locks: list[str] = []

        def visit_With(self, node: ast.With) -> None:
            names = [n for n in (_lock_name(i) for i in node.items) if n]
            if names and (
                node.lineno in block_pragma_lines
                or node.lineno - 1 in block_pragma_lines
            ):
                names = []
            self.locks.extend(names)
            self.generic_visit(node)
            for _ in names:
                self.locks.pop()

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            # A def under a lock runs LATER — fresh lock scope inside.
            saved, self.locks = self.locks, []
            _SymbolWalker.visit_FunctionDef(self, node)
            self.locks = saved

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node: ast.Lambda) -> None:
            saved, self.locks = self.locks, []
            self.generic_visit(node)
            self.locks = saved

        def visit_Call(self, node: ast.Call) -> None:
            if self.locks:
                label = _blocking_call_label(node)
                if label:
                    findings.append(
                        Finding(
                            "LOCK-IO",
                            info.path,
                            node.lineno,
                            f"blocking call `{label}` inside "
                            f"`with {self.locks[-1]}:` — do the I/O outside "
                            "the critical section",
                            symbol=self.symbol,
                        )
                    )
            self.generic_visit(node)

    V().visit(info.tree)
    return findings


# ---------------------------------------------------------------------------
# GUARDED-BY
# ---------------------------------------------------------------------------

_GUARDED_RE = re.compile(r"#\s*guarded by:\s*([A-Za-z_][A-Za-z0-9_]*)")


@file_rule(
    "GUARDED-BY",
    "attributes annotated `# guarded by: _lock` in __init__ may only be "
    "touched inside `with self._lock:` (or a method pragma'd "
    "`# flscheck: holds=_lock` / named *_locked)",
)
def guarded_by(info: FileInfo, ctx: ProjectContext) -> list[Finding]:
    findings: list[Finding] = []
    holds = [
        (p.line, set(p.names))
        for p in info.pragmas
        if p.kind == "holds"
    ]

    for cls in [n for n in ast.walk(info.tree) if isinstance(n, ast.ClassDef)]:
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        if init is None:
            continue
        guarded: dict[str, str] = {}  # attr -> lock attr name
        for node in ast.walk(init):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    m = _GUARDED_RE.search(info.lines[node.lineno - 1])
                    if m:
                        guarded[t.attr] = m.group(1)
        if not guarded:
            continue

        for meth in [
            n
            for n in cls.body
            if isinstance(n, ast.FunctionDef) and n.name != "__init__"
        ]:
            end = getattr(meth, "end_lineno", meth.lineno)
            held_by_pragma = {
                name
                for line, names in holds
                if meth.lineno <= line <= end
                for name in names
            }
            if meth.name.endswith("_locked"):
                # Documented caller-holds-the-lock convention.
                held_by_pragma |= set(guarded.values())

            class M(ast.NodeVisitor):
                def __init__(self) -> None:
                    self.held: list[str] = list(held_by_pragma)

                def visit_With(self, node: ast.With) -> None:
                    names = []
                    for item in node.items:
                        chain = _dotted(item.context_expr)
                        if len(chain) == 2 and chain[0] == "self":
                            names.append(chain[1])
                    self.held.extend(names)
                    self.generic_visit(node)
                    for _ in names:
                        self.held.pop()

                def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
                    return  # nested defs run later, out of this lock scope

                visit_AsyncFunctionDef = visit_FunctionDef

                def visit_Attribute(self, node: ast.Attribute) -> None:
                    if (
                        isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr in guarded
                        and guarded[node.attr] not in self.held
                    ):
                        findings.append(
                            Finding(
                                "GUARDED-BY",
                                info.path,
                                node.lineno,
                                f"`self.{node.attr}` is guarded by "
                                f"`{guarded[node.attr]}` but touched outside "
                                f"`with self.{guarded[node.attr]}:`",
                                symbol=f"{cls.name}.{meth.name}",
                            )
                        )
                    self.generic_visit(node)

            walker = M()
            for stmt in meth.body:  # not meth itself: its own visit_
                walker.visit(stmt)  # FunctionDef guard would skip the body
    return findings


# ---------------------------------------------------------------------------
# KNOB-SYNC
# ---------------------------------------------------------------------------

# Flag -> (config class, field) renames the parsers use on purpose.
_FLAG_ALIASES = {
    "max_new_tokens": ("ServeConfig", "default_max_new_tokens"),
    "deadline_s": ("ServeConfig", "default_deadline_s"),
    # store_true negation: the flag DISABLES the stagger field.
    "autoscale_no_stagger": ("AutoscaleConfig", "stagger"),
}
_CHAOS_PREFIX = "chaos_"
_PRESSURE_PREFIX = "pressure_"
_SCHED_PREFIX = "sched_"
_SLO_PREFIX = "slo_"
_ADAPTER_PREFIX = "adapter_"
_AUTOSCALE_PREFIX = "autoscale_"

# cli.py functions that thread parsed args into config constructions.
_BATCH_READERS = (
    "config_from_args",
    "_fault_config_from_args",
    "_pressure_config_from_args",
    "_adapter_config_from_args",
    "main",
)
_SERVE_READERS = (
    "serve_main",
    "_fault_config_from_args",
    "_pressure_config_from_args",
    "_adapter_config_from_args",
    "_sched_config_from_args",
    "_slo_config_from_args",
    "_autoscale_config_from_args",
)


def _class_fields(tree: ast.Module, class_name: str) -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {
                n.target.id
                for n in node.body
                if isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name)
            }
    return set()


def _module_str_set(tree: ast.Module, name: str) -> set[str]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            value = node.value
            if (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id in ("frozenset", "set", "tuple", "list")
            ):
                if not value.args:
                    return set()
                value = value.args[0]
            try:
                return set(ast.literal_eval(value))
            except ValueError:
                return set()
    return set()


def _parser_flags(tree: ast.Module) -> dict[str, dict[str, int]]:
    """function name -> {flag: line} of add_argument("--flag") calls,
    with one level of helper-function resolution (a builder that calls
    ``_add_robustness_flags(p)`` owns those flags too)."""
    own: dict[str, dict[str, int]] = {}
    calls: dict[str, set[str]] = {}
    for fn in [n for n in tree.body if isinstance(n, ast.FunctionDef)]:
        flags: dict[str, int] = {}
        called: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("--")
                ):
                    flags[node.args[0].value[2:]] = node.lineno
                elif isinstance(node.func, ast.Name):
                    called.add(node.func.id)
        own[fn.name] = flags
        calls[fn.name] = called
    resolved: dict[str, dict[str, int]] = {}
    for name, flags in own.items():
        merged = dict(flags)
        for helper in calls[name]:
            merged.update(own.get(helper, {}))
        resolved[name] = merged
    return resolved


def _args_reads(tree: ast.Module) -> dict[str, dict[str, int]]:
    """function name -> {attr: line} of ``args.<attr>`` reads."""
    out: dict[str, dict[str, int]] = {}
    for fn in [n for n in tree.body if isinstance(n, ast.FunctionDef)]:
        reads: dict[str, int] = {}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "args"
            ):
                reads.setdefault(node.attr, node.lineno)
        out[fn.name] = reads
    return out


@project_rule(
    "KNOB-SYNC",
    "every FrameworkConfig/ServeConfig/SchedConfig/SLOConfig/AutoscaleConfig/"
    "FaultConfig/PressureConfig/AdapterConfig flag exists in both CLI parsers "
    "(or is declared single-parser; serving-only classes are exempt), maps to "
    "a real field, and is threaded into the construction",
)
def knob_sync(ctx: ProjectContext) -> list[Finding]:
    cli = ctx.get("cli.py")
    config = ctx.get("config.py")
    findings: list[Finding] = []
    if cli is None or config is None:
        missing = "cli.py" if cli is None else "config.py"
        return [
            Finding(
                "KNOB-SYNC", missing, 1, f"{missing} not found at the package root"
            )
        ]

    fw = _class_fields(config.tree, "FrameworkConfig")
    sv = _class_fields(config.tree, "ServeConfig")
    fc = _class_fields(config.tree, "FaultConfig")
    pc = _class_fields(config.tree, "PressureConfig")
    sc = _class_fields(config.tree, "SchedConfig")
    oc = _class_fields(config.tree, "SLOConfig")
    ac = _class_fields(config.tree, "AdapterConfig")
    uc = _class_fields(config.tree, "AutoscaleConfig")
    flags = _parser_flags(cli.tree)
    batch = flags.get("build_parser", {})
    serve = flags.get("build_serve_parser", {})
    reads = _args_reads(cli.tree)
    batch_only = _module_str_set(cli.tree, "BATCH_ONLY_FLAGS")
    serve_only = _module_str_set(cli.tree, "SERVE_ONLY_FLAGS")
    driver = _module_str_set(cli.tree, "DRIVER_FLAGS")

    def map_flag(flag: str, parser_name: str = "batch") -> tuple[str, str] | None:
        """(config class, field) a flag sets, or None for driver flags.

        Parser-aware: one flag NAME may set different config classes per
        parser (``--speculative_k`` is FrameworkConfig's offline-scorer
        knob on the batch parser and ServeConfig's serving-speculation
        knob on the serve parser), so the serve parser resolves
        ServeConfig fields FIRST — a serve flag shadowed by a same-named
        FrameworkConfig field would otherwise validate against the wrong
        class and dodge the serve-side threading checks."""
        if flag in driver:
            return None
        if flag == "chaos":
            return ("FaultConfig", "enabled") if "enabled" in fc else ("?", flag)
        if flag.startswith(_CHAOS_PREFIX) and flag[len(_CHAOS_PREFIX):] in fc:
            return ("FaultConfig", flag[len(_CHAOS_PREFIX):])
        if flag == "pressure":
            return ("PressureConfig", "enabled") if "enabled" in pc else ("?", flag)
        if flag.startswith(_PRESSURE_PREFIX) and flag[len(_PRESSURE_PREFIX):] in pc:
            return ("PressureConfig", flag[len(_PRESSURE_PREFIX):])
        if flag == "sched":
            return ("SchedConfig", "enabled") if "enabled" in sc else ("?", flag)
        if flag.startswith(_SCHED_PREFIX) and flag[len(_SCHED_PREFIX):] in sc:
            return ("SchedConfig", flag[len(_SCHED_PREFIX):])
        if flag == "slo":
            return ("SLOConfig", "enabled") if "enabled" in oc else ("?", flag)
        if flag.startswith(_SLO_PREFIX) and flag[len(_SLO_PREFIX):] in oc:
            return ("SLOConfig", flag[len(_SLO_PREFIX):])
        if flag == "autoscale":
            return (
                ("AutoscaleConfig", "enabled") if "enabled" in uc else ("?", flag)
            )
        if (
            flag.startswith(_AUTOSCALE_PREFIX)
            and flag[len(_AUTOSCALE_PREFIX):] in uc
        ):
            return ("AutoscaleConfig", flag[len(_AUTOSCALE_PREFIX):])
        # AdapterConfig (multi-tenant LoRA, adapters/): a SHARED runtime
        # subsystem like FaultConfig/PressureConfig, so adapter_ flags
        # fall through to the both-parsers requirement below.
        if flag.startswith(_ADAPTER_PREFIX) and flag[len(_ADAPTER_PREFIX):] in ac:
            return ("AdapterConfig", flag[len(_ADAPTER_PREFIX):])
        if flag in _FLAG_ALIASES:
            cls, field = _FLAG_ALIASES[flag]
            fields = {"ServeConfig": sv, "AutoscaleConfig": uc}.get(cls, fw)
            return (cls, field) if field in fields else ("?", flag)
        if parser_name == "serve" and flag in sv:
            return ("ServeConfig", flag)
        if flag in fw:
            return ("FrameworkConfig", flag)
        if flag in sv:
            return ("ServeConfig", flag)
        return ("?", flag)

    # 1. Every flag maps to a real config field (or is a declared driver
    #    flag), and shared-runtime flags live in BOTH parsers.
    for parser_name, parser, other, other_name, single_ok in (
        ("batch", batch, serve, "serve", batch_only),
        ("serve", serve, batch, "batch", serve_only),
    ):
        for flag, line in sorted(parser.items()):
            mapped = map_flag(flag, parser_name)
            if mapped is None:
                continue
            cls, field = mapped
            if cls == "?":
                findings.append(
                    Finding(
                        "KNOB-SYNC",
                        cli.path,
                        line,
                        f"--{flag} ({parser_name} parser) maps to no "
                        "FrameworkConfig/ServeConfig/FaultConfig/"
                        "PressureConfig field and is not in DRIVER_FLAGS",
                        symbol=f"parser.{parser_name}",
                    )
                )
                continue
            if cls in (
                "ServeConfig", "SchedConfig", "SLOConfig", "AutoscaleConfig"
            ):
                continue  # serving knobs are inherently serve-parser-only
            # "Shared" means the OTHER parser's same-named flag sets the
            # SAME field: a flag name reused for a different config class
            # (serve --speculative_k -> ServeConfig) does not satisfy the
            # both-parsers requirement for this parser's knob.
            shared = flag in other and map_flag(flag, other_name) == mapped
            if not shared and flag not in single_ok:
                findings.append(
                    Finding(
                        "KNOB-SYNC",
                        cli.path,
                        line,
                        f"--{flag} sets {cls}.{field} but exists only in the "
                        f"{parser_name} parser — add it to the other parser or "
                        f"declare it in "
                        f"{'BATCH' if parser_name == 'batch' else 'SERVE'}"
                        "_ONLY_FLAGS with the reason in the comment",
                        symbol=f"parser.{parser_name}",
                    )
                )

    # 2. Declared single-parser sets stay honest. A same-named flag in
    #    the other parser only voids the declaration when it sets the
    #    SAME config field — a reused name over a different class (the
    #    batch/serve --speculative_k pair) keeps both declarations valid.
    for declared, name, parser, parser_name, other, other_name in (
        (batch_only, "BATCH_ONLY_FLAGS", batch, "batch", serve, "serve"),
        (serve_only, "SERVE_ONLY_FLAGS", serve, "serve", batch, "batch"),
    ):
        for flag in sorted(declared):
            if flag not in parser:
                findings.append(
                    Finding(
                        "KNOB-SYNC",
                        cli.path,
                        1,
                        f"{name} declares --{flag} but the flag is not in "
                        "that parser (stale declaration)",
                        symbol=name,
                    )
                )
            elif flag in other and map_flag(flag, other_name) == map_flag(
                flag, parser_name
            ):
                findings.append(
                    Finding(
                        "KNOB-SYNC",
                        cli.path,
                        1,
                        f"{name} declares --{flag} single-parser but it now "
                        "exists in both parsers — drop the declaration",
                        symbol=name,
                    )
                )

    # 3. Parsed flags must be threaded: read as args.<flag> by the
    #    functions that build the configs (a flag that parses but is never
    #    read is a silent no-op — the exact recurring defect).
    for parser_name, parser, readers in (
        ("batch", batch, _BATCH_READERS),
        ("serve", serve, _SERVE_READERS),
    ):
        read_here = {a for r in readers for a in reads.get(r, {})}
        for flag, line in sorted(parser.items()):
            mapped = map_flag(flag, parser_name)
            if mapped is None or mapped[0] == "?":
                continue
            if flag not in read_here:
                findings.append(
                    Finding(
                        "KNOB-SYNC",
                        cli.path,
                        line,
                        f"--{flag} parses in the {parser_name} parser but is "
                        f"never read (args.{flag}) by "
                        f"{'/'.join(readers)} — the flag is a silent no-op",
                        symbol=f"thread.{parser_name}",
                    )
                )

    # 4. args.<attr> reads must exist in the parser feeding that function.
    #    _fault_config_from_args is called from BOTH CLI paths, so its
    #    reads are checked against EACH parser — a union would hide a flag
    #    defined in only one parser (AttributeError on the other path).
    for fn_name, parser_name, parser in (
        ("config_from_args", "batch", batch),
        ("main", "batch", batch),
        ("serve_main", "serve", serve),
        ("_fault_config_from_args", "batch", batch),
        ("_fault_config_from_args", "serve", serve),
        ("_pressure_config_from_args", "batch", batch),
        ("_pressure_config_from_args", "serve", serve),
        ("_adapter_config_from_args", "batch", batch),
        ("_adapter_config_from_args", "serve", serve),
        # Serve-path-only readers: SchedConfig/SLOConfig are serving
        # subsystems, so their reads validate against the serve parser.
        ("_sched_config_from_args", "serve", serve),
        ("_slo_config_from_args", "serve", serve),
        ("_autoscale_config_from_args", "serve", serve),
    ):
        for attr, line in sorted(reads.get(fn_name, {}).items()):
            if attr not in parser:
                findings.append(
                    Finding(
                        "KNOB-SYNC",
                        cli.path,
                        line,
                        f"{fn_name} reads args.{attr} but the {parser_name} "
                        f"parser defines no --{attr} (AttributeError at "
                        "runtime)",
                        symbol=f"read.{fn_name}",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# SITE-REG
# ---------------------------------------------------------------------------

_SITE_CALL_ATTRS = frozenset({"fire", "corrupt_flat", "corrupt_array"})
_DOC_SITE_RE = re.compile(r"^\|\s*`([a-z_]+)`")


@project_rule(
    "SITE-REG",
    "every injector.fire/corrupt_* site literal is in config.FAULT_SITES "
    "and documented in docs/faults.md; every registered site is used",
)
def site_reg(ctx: ProjectContext) -> list[Finding]:
    findings: list[Finding] = []
    config = ctx.get("config.py")
    declared: set[str] = set()
    declared_line = 1
    if config is not None:
        for node in config.tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "FAULT_SITES"
                for t in node.targets
            ):
                try:
                    declared = set(ast.literal_eval(node.value))
                except ValueError:
                    pass
                declared_line = node.lineno
    if not declared:
        return [
            Finding(
                "SITE-REG",
                config.path if config else "config.py",
                declared_line,
                "config.FAULT_SITES not found (fault sites cannot be "
                "validated)",
            )
        ]

    used: dict[str, tuple[str, int]] = {}
    for info in ctx.files.values():
        if info.relkey == "faults/inject.py":
            continue  # the injector fires whatever site string it is handed
        for node in ast.walk(info.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SITE_CALL_ATTRS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                site = node.args[0].value
                used.setdefault(site, (info.path, node.lineno))
                if site not in declared:
                    findings.append(
                        Finding(
                            "SITE-REG",
                            info.path,
                            node.lineno,
                            f"fault site {site!r} fired but not registered in "
                            "config.FAULT_SITES",
                        )
                    )

    docs_path = ctx.repo_root / "docs" / "faults.md"
    if not docs_path.exists():
        findings.append(
            Finding(
                "SITE-REG",
                "docs/faults.md",
                1,
                "docs/faults.md missing — the fault-site table documents "
                "every registered site",
            )
        )
        documented = None
    else:
        documented = set()
        for line in docs_path.read_text().splitlines():
            m = _DOC_SITE_RE.match(line.strip())
            if m:
                documented.add(m.group(1))

    for site in sorted(declared):
        if site not in used:
            findings.append(
                Finding(
                    "SITE-REG",
                    config.path,
                    declared_line,
                    f"FAULT_SITES registers {site!r} but no call site fires "
                    "it (dead registration)",
                )
            )
        if documented is not None and site not in documented:
            findings.append(
                Finding(
                    "SITE-REG",
                    config.path,
                    declared_line,
                    f"fault site {site!r} is missing from the docs/faults.md "
                    "site table",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# EVENT-REG
# ---------------------------------------------------------------------------

# Journal-emit call shapes: the module-level ``obs_events.emit("<kind>",
# ...)`` (any receiver alias) and a bare ``emit("<kind>", ...)`` import.
# Only calls whose FIRST argument is a string literal are vocabulary
# uses; dynamic kinds are the journal's own plumbing (events.py is
# excluded like inject.py is for SITE-REG).
_EVENT_EMIT_NAMES = frozenset({"emit"})
_EVENTS_MODULE = "obs/events.py"


@project_rule(
    "EVENT-REG",
    "every journal event kind literal (`emit(\"<kind>\")`) is declared "
    "in obs/events.EVENT_KINDS and documented in docs/incidents.md's "
    "kinds table; every declared kind is emitted somewhere",
)
def event_reg(ctx: ProjectContext) -> list[Finding]:
    findings: list[Finding] = []
    events = ctx.get(_EVENTS_MODULE)
    declared: set[str] = set()
    declared_line = 1
    if events is not None:
        for node in events.tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "EVENT_KINDS"
                for t in node.targets
            ):
                try:
                    # A dict literal literal_evals to a dict; its key
                    # set is the declared vocabulary.
                    declared = set(ast.literal_eval(node.value))
                except ValueError:
                    pass
                declared_line = node.lineno
    if not declared:
        return [
            Finding(
                "EVENT-REG",
                events.path if events else _EVENTS_MODULE,
                declared_line,
                "obs/events.EVENT_KINDS not found (journal event kinds "
                "cannot be validated)",
            )
        ]

    used: dict[str, tuple[str, int]] = {}
    for info in ctx.files.values():
        if info.relkey == _EVENTS_MODULE:
            continue  # the journal records whatever kind it is handed
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            name = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None
            )
            if name not in _EVENT_EMIT_NAMES:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                continue
            kind = arg.value
            used.setdefault(kind, (info.path, node.lineno))
            if kind not in declared:
                findings.append(
                    Finding(
                        "EVENT-REG",
                        info.path,
                        node.lineno,
                        f"journal event kind {kind!r} emitted but not "
                        "declared in obs/events.EVENT_KINDS",
                    )
                )

    docs_path = ctx.repo_root / "docs" / "incidents.md"
    if not docs_path.exists():
        findings.append(
            Finding(
                "EVENT-REG",
                "docs/incidents.md",
                1,
                "docs/incidents.md missing — the kinds table documents "
                "every declared journal event kind",
            )
        )
        documented = None
    else:
        documented = set()
        for line in docs_path.read_text().splitlines():
            m = _DOC_SITE_RE.match(line.strip())
            if m:
                documented.add(m.group(1))

    for kind in sorted(declared):
        if kind not in used:
            findings.append(
                Finding(
                    "EVENT-REG",
                    events.path,
                    declared_line,
                    f"EVENT_KINDS declares {kind!r} but no call site "
                    "emits it (dead registration)",
                )
            )
        if documented is not None and kind not in documented:
            findings.append(
                Finding(
                    "EVENT-REG",
                    events.path,
                    declared_line,
                    f"journal event kind {kind!r} is missing from the "
                    "docs/incidents.md kinds table",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# EXC-TAXONOMY
# ---------------------------------------------------------------------------

_EXC_SCOPES = ("runtime/", "serve/", "faults/")
_BROAD_NAMES = frozenset({"Exception", "BaseException"})


def _is_broad(handler: ast.ExceptHandler) -> str | None:
    t = handler.type
    if t is None:
        return "bare except"
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        chain = _dotted(n)
        if chain and chain[-1] in _BROAD_NAMES:
            return f"except {chain[-1]}"
    return None


def _walk_pruned(root: ast.AST):
    """``ast.walk`` that never descends into nested def/lambda bodies: a
    ``raise`` scheduled inside a nested function is not the handler itself
    raising (it runs later, if ever), so it neither excuses a swallow nor
    needs `from` chaining."""
    if isinstance(root, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)


def _contains_raise(body: list[ast.stmt]) -> bool:
    for stmt in body:
        for node in _walk_pruned(stmt):
            if isinstance(node, ast.Raise):
                return True
    return False


@file_rule(
    "EXC-TAXONOMY",
    "hot paths (runtime/, serve/, faults/) must not swallow broad excepts "
    "without a pragma; re-raises of new exceptions must chain `from`",
)
def exc_taxonomy(info: FileInfo, ctx: ProjectContext) -> list[Finding]:
    if not info.relkey.startswith(_EXC_SCOPES):
        return []
    findings: list[Finding] = []

    class V(_SymbolWalker):
        def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
            broad = _is_broad(node)
            if broad and not _contains_raise(node.body):
                findings.append(
                    Finding(
                        "EXC-TAXONOMY",
                        info.path,
                        node.lineno,
                        f"`{broad}` swallows and continues — narrow it to the "
                        "typed errors the handler really expects "
                        "(ShardLoadError/ShardCorruptError/OSError/queue."
                        "Empty/...), or pragma with the degrade rationale",
                        symbol=self.symbol,
                    )
                )
            for stmt in node.body:
                for sub in _walk_pruned(stmt):
                    if (
                        isinstance(sub, ast.Raise)
                        and isinstance(sub.exc, ast.Call)
                        and sub.cause is None
                    ):
                        findings.append(
                            Finding(
                                "EXC-TAXONOMY",
                                info.path,
                                sub.lineno,
                                "raising a new exception inside an except "
                                "block must chain the original "
                                "(`raise X(...) from err`) so both "
                                "tracebacks survive",
                                symbol=self.symbol,
                            )
                        )
            self.generic_visit(node)

    V().visit(info.tree)
    return findings


# ---------------------------------------------------------------------------
# COUNTER-EXPORT
# ---------------------------------------------------------------------------

_EXPORT_METHODS = frozenset({"stats", "snapshot"})
_INTEGRITY_RECEIVERS = frozenset({"integrity", "_integrity"})


def _class_registered_methods(cls: ast.ClassDef) -> frozenset:
    """Method names THIS class registers as metrics-registry sources:
    a ``<reg>.register("name", self.method)`` call anywhere in the class
    body marks ``method`` as one of the class's export surfaces
    (obs/registry.py collects registered sources into the Prometheus
    exposition / --metrics_out dump), so a counter that reaches such a
    method IS exported — the registry path satisfies COUNTER-EXPORT
    exactly like stats()/snapshot() do. Scoped to ``self.<method>``
    registrations inside the SAME class on purpose: a project-wide bag of
    bare method names would let any class whose method merely shares a
    name with someone else's registered source pass unexported."""
    names: set[str] = set()
    for node in ast.walk(cls):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "register"
            and len(node.args) >= 2
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and isinstance(node.args[1], ast.Attribute)
            and isinstance(node.args[1].value, ast.Name)
            and node.args[1].value.id == "self"
        ):
            names.add(node.args[1].attr)
    return frozenset(names)


def _export_names(fns: list[ast.FunctionDef]) -> tuple[set[str], set[str]]:
    """(self.<attr> names, string constants) the export methods mention —
    exact AST nodes, so `self.hits_total` does not pass for `self.hits`
    and a counter named only in a comment/docstring line doesn't count."""
    attrs: set[str] = set()
    strs: set[str] = set()
    for fn in fns:
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                attrs.add(node.attr)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                strs.add(node.value)
    return attrs, strs


@project_rule(
    "COUNTER-EXPORT",
    "counters a class increments (self.x += n) must appear in its "
    "stats()/snapshot() export or in a method the class itself registers "
    "as a metrics-registry source (register(\"name\", self.method)); "
    "IntegrityRecorder.count() names must be registered in its KEYS",
)
def counter_export(ctx: ProjectContext) -> list[Finding]:
    findings: list[Finding] = []

    # 1. Class-attribute counters vs the class's export methods: the
    #    canonical stats()/snapshot() names, plus any method THE CLASS
    #    ITSELF registers into a metrics registry
    #    (``reg.register("src", self.method)``) — registered sources land
    #    in the Prometheus exposition and the --metrics_out dump, which
    #    is precisely "exported".
    for info in ctx.files.values():
        for cls in [n for n in ast.walk(info.tree) if isinstance(n, ast.ClassDef)]:
            registered = _class_registered_methods(cls)
            exporters = [
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
                and (n.name in _EXPORT_METHODS or n.name in registered)
            ]
            if not exporters:
                continue
            export_attrs, export_strs = _export_names(exporters)
            seen: set[str] = set()
            for meth in [n for n in cls.body if isinstance(n, ast.FunctionDef)]:
                for node in ast.walk(meth):
                    if (
                        isinstance(node, ast.AugAssign)
                        and isinstance(node.op, (ast.Add, ast.Sub))
                        and isinstance(node.target, ast.Attribute)
                        and isinstance(node.target.value, ast.Name)
                        and node.target.value.id == "self"
                        and not node.target.attr.startswith("_")
                        and node.target.attr not in seen
                    ):
                        attr = node.target.attr
                        seen.add(attr)
                        if attr not in export_attrs and attr not in export_strs:
                            findings.append(
                                Finding(
                                    "COUNTER-EXPORT",
                                    info.path,
                                    node.lineno,
                                    f"counter `self.{attr}` is incremented but "
                                    f"never exported by {cls.name}."
                                    f"{'/'.join(m.name for m in exporters)}()",
                                    symbol=f"{cls.name}.{meth.name}",
                                )
                            )

    # 2. IntegrityRecorder counter names must be in its KEYS registry.
    metrics = ctx.get("utils/metrics.py")
    keys: set[str] = set()
    if metrics is not None:
        for cls in [
            n for n in ast.walk(metrics.tree) if isinstance(n, ast.ClassDef)
        ]:
            if cls.name != "IntegrityRecorder":
                continue
            for node in cls.body:
                if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "KEYS" for t in node.targets
                ):
                    try:
                        keys = set(ast.literal_eval(node.value))
                    except ValueError:
                        pass
    if keys:
        for info in ctx.files.values():
            for node in ast.walk(info.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "count"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    recv = _dotted(node.func)
                    if len(recv) >= 2 and recv[-2] in _INTEGRITY_RECEIVERS:
                        name = node.args[0].value
                        if name not in keys:
                            findings.append(
                                Finding(
                                    "COUNTER-EXPORT",
                                    info.path,
                                    node.lineno,
                                    f"integrity counter {name!r} is not in "
                                    "IntegrityRecorder.KEYS — it would count "
                                    "but never export",
                                )
                            )
    return findings


# ---------------------------------------------------------------------------
# DETERMINISM
# ---------------------------------------------------------------------------

_DET_SCOPES = ("faults/", "integrity/")


@file_rule(
    "DETERMINISM",
    "faults/ and integrity/ promise seeded reproducibility: no random.* / "
    "np.random.* / time.time() — derive draws via hash_unit and seeds",
)
def determinism(info: FileInfo, ctx: ProjectContext) -> list[Finding]:
    if not info.relkey.startswith(_DET_SCOPES):
        return []
    findings: list[Finding] = []

    class V(_SymbolWalker):
        def visit_Call(self, node: ast.Call) -> None:
            chain = _dotted(node.func)
            bad = None
            if chain[:1] == ("random",) and len(chain) > 1:
                bad = "random." + ".".join(chain[1:])
            elif chain[:2] in (("np", "random"), ("numpy", "random")):
                bad = ".".join(chain)
            elif chain == ("time", "time"):
                bad = "time.time()"
            if bad:
                findings.append(
                    Finding(
                        "DETERMINISM",
                        info.path,
                        node.lineno,
                        f"`{bad}` in a seeded-reproducibility module — use "
                        "hash_unit(seed-derived key) / time.monotonic so a "
                        "chaos schedule replays bit-for-bit",
                        symbol=self.symbol,
                    )
                )
            self.generic_visit(node)

    V().visit(info.tree)
    return findings


# ---------------------------------------------------------------------------
# QUANT-MANIFEST
# ---------------------------------------------------------------------------


@file_rule(
    "QUANT-MANIFEST",
    "every function that writes a layer safetensors file (st_save_file/"
    "save_file) must record an integrity-manifest entry for it "
    "(integrity_manifest.layer_entry) in the same function — layer_entry "
    "is what stamps the per-layer dtype kind, so a writer that skips it "
    "emits quantized leaf-groups the executor's precision check "
    "(PrecisionMismatch) can never audit",
)
def quant_manifest(info: FileInfo, ctx: ProjectContext) -> list[Finding]:
    findings: list[Finding] = []

    def _is_save(chain: tuple[str, ...]) -> bool:
        return bool(chain) and chain[-1] in ("st_save_file", "save_file")

    def _is_entry(chain: tuple[str, ...]) -> bool:
        return bool(chain) and chain[-1] == "layer_entry"

    class V(_SymbolWalker):
        def _scan(self, fn: ast.AST) -> None:
            # Direct statements only: a nested def is its own scope and
            # is scanned on its own visit (save_params pairs the calls
            # inside its nested _save, which is the pairing that counts).
            saves: list[ast.Call] = []
            paired = False
            for stmt in fn.body:
                for node in _walk_pruned(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = _dotted(node.func)
                    if _is_save(chain):
                        saves.append(node)
                    elif _is_entry(chain):
                        paired = True
            if saves and not paired:
                findings.append(
                    Finding(
                        "QUANT-MANIFEST",
                        info.path,
                        saves[0].lineno,
                        "writes a layer safetensors file without recording "
                        "an integrity_manifest.layer_entry in the same "
                        "function — the manifest's per-layer dtype kind is "
                        "what lets the load path type a precision mismatch",
                        symbol=self.symbol,
                    )
                )

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            self.stack.append(node.name)
            self._scan(node)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

    V().visit(info.tree)
    return findings


# ---------------------------------------------------------------------------
# HYGIENE
# ---------------------------------------------------------------------------


@project_rule(
    "HYGIENE",
    "no package dirs without __init__.py, no stray dirs holding only "
    "__pycache__ (they shadow real packages in greps and imports)",
)
def hygiene(ctx: ProjectContext) -> list[Finding]:
    findings: list[Finding] = []
    for dirpath, dirnames, filenames in os.walk(ctx.package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        rel = os.path.relpath(dirpath, ctx.package_dir)
        try:
            display = os.path.relpath(dirpath, ctx.repo_root)
        except ValueError:
            display = rel
        real_files = [f for f in filenames if not f.endswith(".pyc")]
        if not real_files and not dirnames:
            findings.append(
                Finding(
                    "HYGIENE",
                    display,
                    1,
                    "stray directory (empty or __pycache__-only) — delete it; "
                    "it shadows real modules in greps",
                )
            )
            continue
        if rel != "." and any(f.endswith(".py") for f in real_files):
            if "__init__.py" not in real_files:
                findings.append(
                    Finding(
                        "HYGIENE",
                        display,
                        1,
                        "package directory without __init__.py — modules here "
                        "import inconsistently across tools",
                    )
                )
    return findings
