"""flscheck rule framework: registry, pragmas, baseline, runner, reporters.

Design (mirrors how the perf gate made speed claims un-rottable — here the
claims are *invariants*):

- **Rules** register into one table via :func:`file_rule` (runs once per
  parsed module) or :func:`project_rule` (runs once over the whole file
  set, for cross-file invariants like knob threading). Each returns
  :class:`Finding`s.
- **Pragmas** suppress a finding in place::

      except Exception:  # flscheck: disable=EXC-TAXONOMY: reject-with-reason contract

  A pragma names one or more rules (comma-separated) and MUST carry a
  reason after the colon — a reasonless pragma is itself a finding, so
  suppressions stay auditable. A pragma covers its own line and the line
  directly below it (so it can sit on the statement or on a comment line
  above). ``# flscheck: holds=_lock`` is the GUARDED-BY method-contract
  pragma (see rules.py).
- **Baseline** (``flscheck-baseline.json`` at the repo root) grandfathers
  findings by stable fingerprint — (rule, path, enclosing symbol,
  message), line-number independent. Every entry needs a real reason
  (``TODO``-prefixed reasons are rejected), and an entry that no longer
  matches any finding is an error: fixing a finding forces shrinking the
  baseline, so it only ever ratchets down (CI additionally diffs the
  entry set against the merge base).

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import sys
import tokenize
from pathlib import Path
from typing import Callable, Iterable

BASELINE_NAME = "flscheck-baseline.json"

# Rules the runner itself emits (pragma/baseline hygiene, parse errors).
META_RULES = ("PRAGMA", "BASELINE", "PARSE")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``symbol`` is the enclosing ``Class.method`` (or module) — it anchors
    the fingerprint so baselined findings survive unrelated line drift.
    ``message`` must therefore be stable too: no line numbers in it.
    """

    rule: str
    path: str  # repo-relative posix path (display + fingerprint)
    line: int
    message: str
    symbol: str = ""

    @property
    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.symbol}|{self.message}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def format(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{sym} {self.message}"


@dataclasses.dataclass
class FileInfo:
    """One parsed module handed to rules."""

    relkey: str  # path relative to the package dir ("runtime/executor.py")
    path: str  # display path (repo-relative when under the repo root)
    tree: ast.Module
    lines: list[str]  # raw source lines (1-indexed via lines[line - 1])
    pragmas: list["Pragma"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ProjectContext:
    """What project rules see: every parsed file plus the repo layout."""

    package_dir: Path
    repo_root: Path
    files: dict[str, FileInfo]  # relkey -> FileInfo

    def get(self, relkey: str) -> FileInfo | None:
        return self.files.get(relkey)


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    doc: str
    kind: str  # 'file' | 'project'
    fn: Callable


RULES: dict[str, Rule] = {}


def file_rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = Rule(name, doc, "file", fn)
        return fn

    return deco


def project_rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = Rule(name, doc, "project", fn)
        return fn

    return deco


# ---------------------------------------------------------------------------
# Pragmas
# ---------------------------------------------------------------------------

PRAGMA_RE = re.compile(
    r"#\s*flscheck:\s*(?P<kind>disable|holds)="
    r"(?P<args>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?::\s*(?P<reason>.*\S))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class Pragma:
    line: int
    kind: str  # 'disable' | 'holds'
    names: tuple[str, ...]  # rule names / lock names
    reason: str


def parse_pragmas(lines: list[str]) -> list[Pragma]:
    """Pragmas live in real comments only: the source is tokenized and
    PRAGMA_RE runs over COMMENT tokens, so pragma-shaped text inside a
    string or docstring (this framework's own docs, a test fixture)
    neither suppresses anything nor trips the reason hygiene."""
    reader = io.StringIO("\n".join(lines) + "\n").readline
    try:
        comments = [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(reader)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Every analyzed file already ast-parsed, so this is near
        # unreachable — but a tokenizer edge case must fail toward the
        # raw scan (a phantom pragma is a visible PRAGMA finding; a
        # dropped one would silently unsuppress and fail CI loudly).
        comments = list(enumerate(lines, 1))
    out = []
    for i, text in comments:
        m = PRAGMA_RE.search(text)
        if m:
            names = tuple(s.strip() for s in m.group("args").split(","))
            out.append(Pragma(i, m.group("kind"), names, m.group("reason") or ""))
    return out


def _pragma_findings(info: FileInfo, pragmas: list[Pragma]) -> list[Finding]:
    """Hygiene of the pragmas themselves: known rule names, real reasons."""
    out = []
    for p in pragmas:
        if p.kind == "disable":
            for name in p.names:
                if name not in RULES and name not in META_RULES:
                    out.append(
                        Finding(
                            "PRAGMA",
                            info.path,
                            p.line,
                            f"pragma disables unknown rule {name!r}",
                            symbol="pragma",
                        )
                    )
        # Every suppression carries a reason — holds= exempts GUARDED-BY
        # just as disable= exempts its rules, so it gets the same hygiene.
        if not p.reason or p.reason.upper().startswith("TODO"):
            out.append(
                Finding(
                    "PRAGMA",
                    info.path,
                    p.line,
                    f"{p.kind} pragma needs a reason "
                    f"(flscheck: {p.kind}=<name>: <why this is fine>)",
                    symbol="pragma",
                )
            )
    return out


def _suppressed(finding: Finding, pragmas: list[Pragma]) -> bool:
    for p in pragmas:
        if p.kind != "disable":
            continue
        if p.line in (finding.line, finding.line - 1) and finding.rule in p.names:
            return True
    return False


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: Path) -> tuple[dict[str, dict], list[Finding]]:
    """fingerprint -> entry, plus findings about the baseline file itself."""
    findings: list[Finding] = []
    if not path.exists():
        return {}, findings
    try:
        data = json.loads(path.read_text())
        entries = list(data.get("entries", []))
    except (OSError, ValueError) as e:
        return {}, [
            Finding("BASELINE", path.name, 1, f"unreadable baseline: {e}")
        ]
    by_fp: dict[str, dict] = {}
    for e in entries:
        fp = e.get("fingerprint", "")
        reason = (e.get("reason") or "").strip()
        if not fp:
            findings.append(
                Finding("BASELINE", path.name, 1, f"entry without fingerprint: {e}")
            )
            continue
        if not reason or reason.upper().startswith("TODO"):
            findings.append(
                Finding(
                    "BASELINE",
                    path.name,
                    1,
                    f"entry {fp} ({e.get('rule')}) needs a real reason string",
                )
            )
        by_fp[fp] = e
    return by_fp, findings


def write_baseline(
    path: Path,
    findings: Iterable[Finding],
    old: dict[str, dict],
    extra_entries: Iterable[dict] = (),
) -> None:
    entries = [dict(e) for e in extra_entries]
    written = {e.get("fingerprint") for e in entries}
    for f in findings:
        if f.fingerprint in written:
            # Fingerprints are line-independent, so two identical
            # violations in one symbol share one — and one entry
            # grandfathers both.
            continue
        written.add(f.fingerprint)
        prev = old.get(f.fingerprint, {})
        entries.append(
            {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
                "reason": prev.get("reason", "TODO: justify or fix"),
            }
        )
    entries.sort(key=lambda e: (e["rule"], e["path"], e["fingerprint"]))
    path.write_text(json.dumps({"version": 1, "entries": entries}, indent=2) + "\n")


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def _ensure_rules_loaded() -> None:
    # rules.py imports this module for the registry; import it lazily here
    # so `import core` alone never cycles.
    from flexible_llm_sharding_tpu.analysis import rules  # noqa: F401


def _collect_files(package_dir: Path, repo_root: Path) -> tuple[dict[str, FileInfo], list[Finding]]:
    files: dict[str, FileInfo] = {}
    findings: list[Finding] = []
    for p in sorted(package_dir.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        relkey = p.relative_to(package_dir).as_posix()
        try:
            display = p.relative_to(repo_root).as_posix()
        except ValueError:
            display = relkey
        try:
            source = p.read_text()
            tree = ast.parse(source, filename=str(p))
        except (OSError, SyntaxError) as e:
            findings.append(Finding("PARSE", display, getattr(e, "lineno", 1) or 1, str(e)))
            continue
        lines = source.splitlines()
        files[relkey] = FileInfo(relkey, display, tree, lines, parse_pragmas(lines))
    return files, findings


@dataclasses.dataclass
class Result:
    findings: list[Finding]  # active (unsuppressed, unbaselined)
    baselined: list[Finding]
    suppressed: int  # pragma-suppressed count

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        def enc(f: Finding) -> dict:
            return {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "symbol": f.symbol,
                "message": f.message,
                "fingerprint": f.fingerprint,
            }

        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "version": 1,
            "ok": self.ok,
            "findings": [enc(f) for f in self.findings],
            "baselined": [enc(f) for f in self.baselined],
            "suppressed_by_pragma": self.suppressed,
            "counts": counts,
        }

    def format_text(self) -> str:
        out = [f.format() for f in sorted(self.findings, key=lambda f: (f.path, f.line, f.rule))]
        summary = (
            f"flscheck: {len(self.findings)} finding(s), "
            f"{len(self.baselined)} baselined, "
            f"{self.suppressed} pragma-suppressed"
        )
        return "\n".join(out + [summary])


def run(
    package_dir: str | os.PathLike,
    repo_root: str | os.PathLike | None = None,
    baseline_path: str | os.PathLike | None = None,
    select: Iterable[str] | None = None,
) -> Result:
    """Analyze ``package_dir``; ``select`` limits to the named rules
    (meta rules always run). ``baseline_path`` None resolves to
    ``<repo_root>/flscheck-baseline.json``; pass ``""`` to disable."""
    _ensure_rules_loaded()
    package_dir = Path(package_dir)
    repo_root = Path(repo_root) if repo_root is not None else package_dir.parent
    selected = set(select) if select else None

    files, findings = _collect_files(package_dir, repo_root)
    ctx = ProjectContext(package_dir=package_dir, repo_root=repo_root, files=files)

    for info in files.values():
        findings.extend(_pragma_findings(info, info.pragmas))

    raw: list[Finding] = []
    for rule in RULES.values():
        if selected is not None and rule.name not in selected:
            continue
        if rule.kind == "file":
            for relkey, info in files.items():
                raw.extend(rule.fn(info, ctx))
        else:
            raw.extend(rule.fn(ctx))

    # Pragma suppression (keyed by display path -> pragmas).
    pragmas_by_path = {info.path: info.pragmas for info in files.values()}
    suppressed = 0
    kept: list[Finding] = []
    for f in raw:
        if _suppressed(f, pragmas_by_path.get(f.path, [])):
            suppressed += 1
        else:
            kept.append(f)

    # Baseline: matched findings drop out; stale entries are errors.
    baselined: list[Finding] = []
    if baseline_path is None:
        baseline_path = repo_root / BASELINE_NAME
    if baseline_path:
        baseline, bl_findings = load_baseline(Path(baseline_path))
        findings.extend(bl_findings)
        matched: set[str] = set()
        active = []
        for f in kept:
            if f.fingerprint in baseline:
                matched.add(f.fingerprint)
                baselined.append(f)
            else:
                active.append(f)
        kept = active
        for fp, e in sorted(baseline.items()):
            if fp in matched:
                continue
            if selected is not None and e.get("rule") not in selected:
                # The entry's rule did not run under --select, so its
                # finding could not have been produced — staleness is only
                # judgeable on a full run.
                continue
            findings.append(
                Finding(
                    "BASELINE",
                    Path(baseline_path).name,
                    1,
                    f"stale entry {fp} ({e.get('rule')} at {e.get('path')}) "
                    "matches no finding — remove it (the baseline only shrinks)",
                )
            )

    # De-duplicate identical findings (two rules or passes reporting the
    # same thing at the same spot) while keeping order stable.
    seen: set[tuple] = set()
    unique: list[Finding] = []
    for f in findings + kept:
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return Result(findings=unique, baselined=baselined, suppressed=suppressed)


def analyze_source(
    source: str, relkey: str = "mod.py", select: Iterable[str] | None = None
) -> list[Finding]:
    """Run the FILE rules (plus pragma handling) over one source string —
    the unit-test harness for per-file rules."""
    _ensure_rules_loaded()
    tree = ast.parse(source)
    lines = source.splitlines()
    info = FileInfo(relkey, relkey, tree, lines, parse_pragmas(lines))
    ctx = ProjectContext(Path("."), Path("."), {relkey: info})
    pragmas = info.pragmas
    findings = _pragma_findings(info, pragmas)
    selected = set(select) if select else None
    for rule in RULES.values():
        if rule.kind != "file":
            continue
        if selected is not None and rule.name not in selected:
            continue
        findings.extend(f for f in rule.fn(info, ctx) if not _suppressed(f, pragmas))
    return findings


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_check_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="flscheck",
        description="Project-invariant static analyzer (lock discipline, "
        "knob threading, fault-site registry, exception taxonomy, counter "
        "export, determinism, repo hygiene). Exit 0 = clean.",
    )
    p.add_argument("--json", action="store_true", help="machine-readable report")
    p.add_argument(
        "--select",
        type=str,
        default="",
        help="comma list of rule names to run (default: all)",
    )
    p.add_argument(
        "--baseline",
        type=str,
        default=None,
        help=f"baseline file (default <repo>/{BASELINE_NAME}); 'none' disables",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from the current findings (existing "
        "reasons are preserved by fingerprint; new entries get a TODO "
        "reason you must replace before CI passes)",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog")
    p.add_argument(
        "--root",
        type=str,
        default=None,
        help="package dir to analyze (default: this installed package)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    _ensure_rules_loaded()
    args = build_check_parser().parse_args(argv)
    if args.list_rules:
        for r in sorted(RULES.values(), key=lambda r: r.name):
            print(f"{r.name:16s} [{r.kind}] {r.doc}")
        return 0
    package_dir = Path(args.root) if args.root else Path(__file__).resolve().parent.parent
    repo_root = package_dir.parent
    baseline_path: str | Path | None
    if args.baseline == "none":
        baseline_path = ""
    elif args.baseline:
        baseline_path = Path(args.baseline)
    else:
        baseline_path = repo_root / BASELINE_NAME
    select = [s for s in args.select.split(",") if s] or None
    if select:
        unknown = [s for s in select if s not in RULES and s not in META_RULES]
        if unknown:
            # A typo'd --select would otherwise run zero rules and report
            # a clean pass — fail loudly like a bad chaos site name does.
            print(
                "flscheck: unknown rule(s) in --select: "
                f"{', '.join(unknown)} (see --list-rules)",
                file=sys.stderr,
            )
            return 2

    if args.write_baseline:
        if not baseline_path:
            print(
                "flscheck: --write-baseline needs a baseline file "
                "(drop --baseline none)",
                file=sys.stderr,
            )
            return 2
        # Findings computed WITHOUT the baseline become the new baseline.
        res = run(package_dir, repo_root, baseline_path="", select=select)
        old, _ = load_baseline(Path(baseline_path))
        writable = [f for f in res.findings if f.rule not in META_RULES]
        kept_old = []
        if select:
            # Only the selected rules re-ran: entries for every OTHER rule
            # were neither confirmed nor refuted, so carry them over
            # verbatim instead of silently mass-deleting them.
            kept_old = [
                e for e in old.values() if e.get("rule") not in set(select)
            ]
        write_baseline(Path(baseline_path), writable, old, extra_entries=kept_old)
        print(
            f"wrote {len(writable) + len(kept_old)} entries to {baseline_path}"
            + (f" ({len(kept_old)} carried over from unselected rules)" if kept_old else ""),
            file=sys.stderr,
        )
        return 0

    res = run(package_dir, repo_root, baseline_path=baseline_path, select=select)
    if args.json:
        print(json.dumps(res.to_json(), indent=2))
    else:
        print(res.format_text())
    return 0 if res.ok else 1
