"""Sharded training over a device mesh.

The reference is inference-only (no optimizer, loss, or backward pass anywhere
in its 3 files — SURVEY.md §0), but a framework needs a training path to be
more than a scoring tool, and the multi-chip sharding design (parallel/
sharding.py) is exercised hardest by the backward pass: TP's row/column layout
must round-trip gradients with exactly one psum per projection pair, and DP
gradients must reduce over the ``dp`` axis. XLA derives all of those
collectives from the NamedSharding annotations below — nothing here issues a
collective by hand.

A real training loop needs more than one step function; this module provides:

- :func:`make_train_step` — jitted step, optional gradient accumulation
  (``accum_steps`` microbatches scanned per update, grads averaged).
- :func:`make_optimizer` / :func:`make_lr_schedule` — AdamW with global-norm
  clipping and warmup + cosine/linear decay.
- :func:`save_train_state` / :func:`restore_train_state` — orbax-backed
  train-state checkpointing (params + optimizer state + step), restorable
  onto a fresh mesh.

Usage:
    mesh = make_mesh({"dp": 2, "tp": 4})
    opt = make_optimizer(peak_lr=3e-4, warmup_steps=100, total_steps=10_000)
    state = TrainState.create(cfg, params, opt, mesh)
    step = make_train_step(cfg, opt, mesh)
    state, loss = step(state, batch)   # batch: int32 [B, L+1] token ids
    save_train_state(state, "ckpt/step_1000")
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flexible_llm_sharding_tpu.config import LlamaConfig
from flexible_llm_sharding_tpu.models import llama
from flexible_llm_sharding_tpu.parallel.sharding import (
    data_spec,
    param_specs,
    tree_shardings,
)

Params = dict[str, Any]


def token_cross_entropy(
    logits: jax.Array, targets: jax.Array, pad_id: int | None = None
) -> jax.Array:
    """Mean next-token cross-entropy from logits [..., L, V] and int targets
    [..., L]. With ``pad_id``, positions whose target is pad are excluded
    from the mean (right-padded ragged batches). Shared by the monolithic
    loss below and the layer-streamed trainer's tail (training_stream.py) so
    the two paths cannot drift."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if pad_id is None:
        return -jnp.mean(ll)
    keep = (targets != pad_id).astype(jnp.float32)
    return -jnp.sum(ll * keep) / jnp.maximum(jnp.sum(keep), 1.0)


def next_token_loss(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,
    dtype=jnp.bfloat16,
    pad_id: int | None = None,
) -> jax.Array:
    """Mean next-token cross-entropy. tokens: int32 [B, L+1] (inputs=: -1,
    targets=1:). Logits come back float32 from ``forward_full``."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = llama.forward_full(params, cfg, inputs, dtype=dtype)
    return token_cross_entropy(logits, targets, pad_id)


@dataclasses.dataclass
class TrainState:
    """Parameters + optimizer state, both sharded over the mesh."""

    params: Params
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(
        cls,
        cfg: LlamaConfig,
        params: Params,
        optimizer: optax.GradientTransformation,
        mesh: Mesh | None = None,
        tp: str | None = "tp",
    ) -> "TrainState":
        if mesh is not None:
            shardings = tree_shardings(
                mesh, param_specs(cfg, tp=tp if tp in mesh.axis_names else None)
            )
            params = jax.device_put(params, shardings)
        opt_state = optimizer.init(params)
        return cls(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))


def make_train_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh | None = None,
    dp: str | None = "dp",
    dtype=jnp.bfloat16,
    pad_id: int | None = None,
    accum_steps: int = 1,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, jax.Array]]:
    """Build the jitted train step.

    With a mesh: batch is sharded over ``dp``; the params' TP layout comes
    from how ``TrainState.create`` placed them (Megatron specs in
    parallel/sharding.py). The DP gradient all-reduce and TP activation
    collectives are inserted by XLA from the sharding annotations — the
    TPU-native replacement for a NCCL/MPI backend (SURVEY.md §2.3).

    ``accum_steps > 1``: the batch arrives as [accum_steps, B, L+1] and the
    update applies the microbatch-averaged gradient.
    """

    dp_ax = dp if mesh is not None and dp in mesh.axis_names else None

    def grad_of(params, tokens):
        if mesh is not None and dp_ax is not None:
            # Pin the batch layout so a replicated host array still runs DP.
            tokens = jax.lax.with_sharding_constraint(
                tokens, NamedSharding(mesh, data_spec(dp=dp_ax))
            )
        return jax.value_and_grad(next_token_loss)(
            params, cfg, tokens, dtype, pad_id
        )

    def step_fn(state: TrainState, tokens: jax.Array):
        if accum_steps > 1:
            # tokens [accum_steps, B, L+1]: scan the microbatches, average
            # grads — one optimizer update per accum_steps forwards, the
            # standard trick for an effective batch HBM can't hold at once.
            def micro(carry, mb):
                loss_sum, gsum = carry
                l, g = grad_of(state.params, mb)
                return (loss_sum + l, jax.tree.map(jnp.add, gsum, g)), None

            init = (
                jnp.zeros((), jnp.float32),
                jax.tree.map(jnp.zeros_like, state.params),
            )
            (loss, grads), _ = jax.lax.scan(micro, init, tokens)
            loss = loss / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
        else:
            loss, grads = grad_of(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    # "Computation follows data": TrainState.create already placed params (and
    # therefore opt_state) with the TP NamedShardings, and shard_batch places
    # the tokens over dp — jit compiles against those operand shardings and XLA
    # inserts the DP grad all-reduce + TP activation collectives. Donation
    # reuses the old params/opt-state HBM for the new state.
    return jax.jit(step_fn, donate_argnums=(0,))


def make_lr_schedule(
    peak_lr: float,
    warmup_steps: int = 0,
    total_steps: int | None = None,
    kind: str = "cosine",
    end_scale: float = 0.1,
):
    """Linear warmup to ``peak_lr`` then cosine/linear decay to
    ``peak_lr * end_scale`` over ``total_steps`` (constant after warmup if
    ``total_steps`` is None)."""
    if warmup_steps == 0 and total_steps is None:
        return peak_lr
    warm = optax.linear_schedule(0.0, peak_lr, max(warmup_steps, 1))
    if total_steps is None:
        decay = optax.constant_schedule(peak_lr)
    else:
        decay_steps = max(total_steps - warmup_steps, 1)
        if kind == "cosine":
            decay = optax.cosine_decay_schedule(peak_lr, decay_steps, alpha=end_scale)
        elif kind == "linear":
            decay = optax.linear_schedule(peak_lr, peak_lr * end_scale, decay_steps)
        else:
            raise ValueError(f"unknown schedule kind {kind!r}")
    return optax.join_schedules([warm, decay], [warmup_steps])


def make_optimizer(
    peak_lr: float = 3e-4,
    weight_decay: float = 0.1,
    warmup_steps: int = 0,
    total_steps: int | None = None,
    grad_clip: float = 1.0,
    b1: float = 0.9,
    b2: float = 0.95,
    schedule_kind: str = "cosine",
) -> optax.GradientTransformation:
    """The standard LLM recipe: global-norm clip -> AdamW on a warmup +
    decay schedule."""
    lr = make_lr_schedule(peak_lr, warmup_steps, total_steps, schedule_kind)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate=lr, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def save_train_state(state: TrainState, path: str) -> None:
    """Checkpoint the full train state (params + optimizer moments + step)
    with orbax; sharded arrays are gathered/written per-shard by orbax."""
    import os

    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), state, force=True)
    # StandardCheckpointer writes asynchronously; block so the checkpoint is
    # durable when this returns (crash-consistency is the whole point).
    ckptr.wait_until_finished()


def restore_train_state(
    path: str,
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh | None = None,
    tp: str | None = "tp",
    dtype=jnp.float32,
) -> TrainState:
    """Restore a :func:`save_train_state` checkpoint and (re)place it on a
    mesh — the mesh may differ from the one the checkpoint was written on
    (resharding is a device_put). The restored optimizer state must come
    from the same optimizer recipe (same pytree structure)."""
    import os

    import orbax.checkpoint as ocp

    abs_params = jax.eval_shape(
        lambda: llama.init_params(jax.random.PRNGKey(0), cfg, dtype)
    )
    abs_state = jax.eval_shape(
        lambda p: TrainState(
            params=p, opt_state=optimizer.init(p), step=jnp.zeros((), jnp.int32)
        ),
        abs_params,
    )
    restored = ocp.StandardCheckpointer().restore(os.path.abspath(path), abs_state)
    if mesh is None:
        return restored
    # Re-place on the mesh: params get the Megatron specs; optimizer moments
    # mirror their parameter's sharding (template from a throwaway init).
    # Leaves the template left on the default device (e.g. step counters from
    # optimizer.init's eager zeros) must be REPLICATED over the mesh —
    # restored arrays are committed, and jit rejects mixed device sets.
    tmpl = TrainState.create(cfg, restored.params, optimizer, mesh=mesh, tp=tp)
    rep = NamedSharding(mesh, P())

    def place(t, r):
        if (
            isinstance(t, jax.Array)
            and getattr(t.sharding, "num_devices", 1) == mesh.size
        ):
            return jax.device_put(r, t.sharding)
        return jax.device_put(r, rep)

    opt_state = jax.tree.map(place, tmpl.opt_state, restored.opt_state)
    return TrainState(
        params=tmpl.params,
        opt_state=opt_state,
        step=jax.device_put(restored.step, rep),
    )


def shard_batch(mesh: Mesh, tokens, dp: str | None = "dp", sp: str | None = None):
    """Place a host token batch [B, L] onto the mesh, batch over ``dp``."""
    dp_ax = dp if dp in mesh.axis_names else None
    sp_ax = sp if sp is not None and sp in mesh.axis_names else None
    return jax.device_put(tokens, NamedSharding(mesh, data_spec(dp=dp_ax, sp=sp_ax)))


# TrainState must be a pytree for jit/shardings to map over it.
jax.tree_util.register_dataclass(
    TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[]
)

__all__ = [
    "TrainState",
    "make_train_step",
    "make_optimizer",
    "make_lr_schedule",
    "next_token_loss",
    "save_train_state",
    "restore_train_state",
    "shard_batch",
]
