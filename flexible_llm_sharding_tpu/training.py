"""Sharded training step over a device mesh.

The reference is inference-only (no optimizer, loss, or backward pass anywhere
in its 3 files — SURVEY.md §0), but a framework needs a training path to be
more than a scoring tool, and the multi-chip sharding design (parallel/
sharding.py) is exercised hardest by the backward pass: TP's row/column layout
must round-trip gradients with exactly one psum per projection pair, and DP
gradients must reduce over the ``dp`` axis. XLA derives all of those
collectives from the NamedSharding annotations below — nothing here issues a
collective by hand.

Usage:
    mesh = make_mesh({"dp": 2, "tp": 4})
    state = TrainState.create(cfg, params, optax.adamw(1e-4), mesh)
    step = make_train_step(cfg, optimizer, mesh)
    state, loss = step(state, batch)   # batch: int32 [B, L+1] token ids
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flexible_llm_sharding_tpu.config import LlamaConfig
from flexible_llm_sharding_tpu.models import llama
from flexible_llm_sharding_tpu.parallel.sharding import (
    data_spec,
    param_specs,
    tree_shardings,
)

Params = dict[str, Any]


def next_token_loss(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,
    dtype=jnp.bfloat16,
    pad_id: int | None = None,
) -> jax.Array:
    """Mean next-token cross-entropy. tokens: int32 [B, L+1] (inputs=: -1,
    targets=1:). With ``pad_id``, positions whose target is pad are excluded
    from the mean (right-padded ragged batches). Logits come back float32
    from ``forward_full``."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = llama.forward_full(params, cfg, inputs, dtype=dtype)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if pad_id is None:
        return -jnp.mean(ll)
    keep = (targets != pad_id).astype(jnp.float32)
    return -jnp.sum(ll * keep) / jnp.maximum(jnp.sum(keep), 1.0)


@dataclasses.dataclass
class TrainState:
    """Parameters + optimizer state, both sharded over the mesh."""

    params: Params
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(
        cls,
        cfg: LlamaConfig,
        params: Params,
        optimizer: optax.GradientTransformation,
        mesh: Mesh | None = None,
        tp: str | None = "tp",
    ) -> "TrainState":
        if mesh is not None:
            shardings = tree_shardings(
                mesh, param_specs(cfg, tp=tp if tp in mesh.axis_names else None)
            )
            params = jax.device_put(params, shardings)
        opt_state = optimizer.init(params)
        return cls(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))


def make_train_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh | None = None,
    dp: str | None = "dp",
    dtype=jnp.bfloat16,
    pad_id: int | None = None,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, jax.Array]]:
    """Build the jitted train step.

    With a mesh: batch is sharded over ``dp``; the params' TP layout comes
    from how ``TrainState.create`` placed them (Megatron specs in
    parallel/sharding.py). The DP gradient all-reduce and TP activation
    collectives are inserted by XLA from the sharding annotations — the
    TPU-native replacement for a NCCL/MPI backend (SURVEY.md §2.3).
    """

    dp_ax = dp if mesh is not None and dp in mesh.axis_names else None

    def step_fn(state: TrainState, tokens: jax.Array):
        if mesh is not None and dp_ax is not None:
            # Pin the batch layout so a replicated host array still runs DP.
            tokens = jax.lax.with_sharding_constraint(
                tokens, NamedSharding(mesh, data_spec(dp=dp_ax))
            )
        loss, grads = jax.value_and_grad(next_token_loss)(
            state.params, cfg, tokens, dtype, pad_id
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    # "Computation follows data": TrainState.create already placed params (and
    # therefore opt_state) with the TP NamedShardings, and shard_batch places
    # the tokens over dp — jit compiles against those operand shardings and XLA
    # inserts the DP grad all-reduce + TP activation collectives. Donation
    # reuses the old params/opt-state HBM for the new state.
    return jax.jit(step_fn, donate_argnums=(0,))


def shard_batch(mesh: Mesh, tokens, dp: str | None = "dp", sp: str | None = None):
    """Place a host token batch [B, L] onto the mesh, batch over ``dp``."""
    dp_ax = dp if dp in mesh.axis_names else None
    sp_ax = sp if sp is not None and sp in mesh.axis_names else None
    return jax.device_put(tokens, NamedSharding(mesh, data_spec(dp=dp_ax, sp=sp_ax)))


# TrainState must be a pytree for jit/shardings to map over it.
jax.tree_util.register_dataclass(
    TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[]
)

__all__ = ["TrainState", "make_train_step", "next_token_loss", "shard_batch"]
