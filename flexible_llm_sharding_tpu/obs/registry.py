"""The metrics registry: one place every subsystem's counters register
into, one machine-readable way out.

Before this module the repo had four disjoint recorder classes
(``ServingMetrics``, ``IntegrityRecorder``, ``RetryRecorder``,
``StepWatchdog``) plus ad-hoc stats dicts on the executor, host cache,
and residency tier, stitched together by hand into a printed stats line.
A router doing health-based draining (ROADMAP item 4) or a CI perf gate
(item 5) needs those signals as *scrapeable data*, not log greps. So:

- ``MetricsRegistry``: named sources (a callable returning a flat dict,
  or any object with ``stats()`` / ``snapshot()``) registered once,
  collected on demand. Collection calls sources OUTSIDE the registry
  lock (a wedged source must not stall every other scraper) and a
  source that raises reports ``{"collect_error": 1}`` instead of taking
  the endpoint down.
- ``prometheus_text()``: the standard text exposition format, one
  ``fls_<source>_<key>`` gauge per numeric leaf (one nested level is
  flattened — per-label retry counts, latency summaries).
- ``MetricsServer``: a tiny threaded HTTP endpoint serving ``/metrics``
  (Prometheus text) and ``/metrics.json`` (the raw collection) — the
  serve engine's ``--metrics_port``. ``port=0`` binds an ephemeral port
  (tests, parallel engines); the bound port is ``server.port``.

``REGISTRY`` is the process-wide instance: the executor, host cache,
residency tier, tracer, and serving metrics all register into it, and
the batch CLI's ``--metrics_out`` dumps it. The serve engine keeps a
per-engine registry too (``ServingMetrics.registry``) so its endpoint
and stats line reflect *that* engine even when several engines have
lived in one process.
"""

from __future__ import annotations

import json
import re
import threading
import weakref

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")


def source_snapshot(source) -> dict:
    """Normalize a registered source to a dict: call it if callable, else
    prefer ``stats()`` over ``snapshot()`` (both are this repo's export
    idioms — flscheck's COUNTER-EXPORT audits exactly these methods)."""
    if callable(source):
        return source() or {}
    for meth in ("stats", "snapshot"):
        fn = getattr(source, meth, None)
        if callable(fn):
            return fn() or {}
    raise TypeError(
        f"metrics source {source!r} is neither callable nor has "
        "stats()/snapshot()"
    )


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: dict[str, object] = {}  # guarded by: _lock

    def register(self, name: str, source) -> None:
        """Register (or replace — last wins, mirroring the process-wide
        cache/tier precedent) a named source."""
        with self._lock:
            self._sources[name] = source

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def unregister_if(self, name: str, source) -> None:
        """Remove ``name`` only while it still maps to ``source`` — the
        teardown form for last-wins mirrors: a dead engine must drop ITS
        registration without yanking a newer engine's."""
        with self._lock:
            if self._sources.get(name) is source:
                del self._sources[name]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._sources)

    def collect(self) -> dict[str, dict]:
        """Snapshot every source: ``{source_name: {key: value}}``. Sources
        run outside the registry lock; a raising source yields a loud
        ``collect_error`` marker instead of propagating."""
        with self._lock:
            sources = dict(self._sources)
        out: dict[str, dict] = {}
        for name in sorted(sources):
            try:
                snap = source_snapshot(sources[name])
            except Exception:
                snap = {"collect_error": 1}
            if snap:
                out[name] = snap
        return out

    # -- exposition --------------------------------------------------------

    def prometheus_text(self, prefix: str = "fls") -> str:
        """Prometheus text exposition: every numeric leaf of ``collect()``
        becomes one gauge named ``<prefix>_<source>_<path>``; one nested
        dict level (per-label retry counts, latency summaries) flattens
        into the name. Non-numeric leaves are skipped."""
        lines: list[str] = []

        def emit(name: str, value) -> None:
            if isinstance(value, bool):
                value = int(value)
            if not isinstance(value, (int, float)):
                return
            metric = _PROM_BAD.sub("_", name)
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {value}")

        for source, snap in self.collect().items():
            for key, value in snap.items():
                if isinstance(value, dict):
                    for sub, sv in value.items():
                        if isinstance(sv, dict):  # per-label tables
                            for leaf, lv in sv.items():
                                emit(
                                    f"{prefix}_{source}_{key}_{sub}_{leaf}",
                                    lv,
                                )
                        else:
                            emit(f"{prefix}_{source}_{key}_{sub}", sv)
                else:
                    emit(f"{prefix}_{source}_{key}", value)
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY


def weak_source(obj, attr: str = "stats"):
    """A registry source reading ``obj.<attr>`` through a weakref: the
    registration must not pin a dead runner (executor, decode generator,
    pipeline) in memory for the process lifetime — a collected instance
    simply disappears from the collection (empty snapshot)."""
    ref = weakref.ref(obj)

    def source() -> dict:
        o = ref()
        if o is None:
            return {}
        val = getattr(o, attr, {})
        return val() if callable(val) else val

    return source


class MetricsServer:
    """Threaded HTTP endpoint over a registry: ``/metrics`` (Prometheus
    text) and ``/metrics.json``. Daemon-threaded; ``close()`` is
    idempotent. Binds ``host:port`` eagerly so a taken port fails at
    construction, not at first scrape."""

    def __init__(
        self, registry: MetricsRegistry, port: int = 0,
        host: str = "127.0.0.1",
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = reg.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = json.dumps(reg.collect()).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # scrapes must not spam the serve log

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-endpoint",
            daemon=True,
        )
        self._thread.start()
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)


__all__ = [
    "REGISTRY",
    "MetricsRegistry",
    "MetricsServer",
    "get_registry",
    "source_snapshot",
]
