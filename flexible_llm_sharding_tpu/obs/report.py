"""Trace analyzer: turn a recorded sweep timeline into the numbers humans
previously eyeballed off stats lines and Perfetto screenshots.

Input is a trace written by ``obs.trace`` (Chrome trace-event JSON or
JSONL — both are auto-detected). Output:

- **link utilization**: fraction of the trace wall the weight stream was
  busy (merged union of ``shard_load`` + ``device_put`` span intervals
  over the wall) — how hard the binding constraint is being driven.
- **overlap efficiency**: ``1 - source_wait / shard_produce`` — the
  fraction of weight-produce time hidden under compute, the same
  definition bench.py derives from executor stats, now computable from
  any run's trace after the fact.
- **per-phase sweep breakdown**: total seconds per span name, plus the
  per-sweep phase profile (grouped by ``sweep_id``) showing where a
  sweep's wall goes.
- **serve latencies**: p50/p95/p99 TTFT and per-token latency from the
  engine's ``ttft`` / ``token_latency`` instant events.

``main()`` backs both the ``cli trace-report`` subcommand and
``scripts/trace_report.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Span names whose intervals constitute "the stream is busy" for link
# utilization. shard_produce is their parent (it additionally covers
# residency waits), so it is excluded from the union to avoid double
# counting; overlap efficiency uses it as the produce denominator.
STREAM_SPAN_NAMES = ("shard_load", "device_put")
PRODUCE_SPAN = "shard_produce"
WAIT_SPAN = "source_wait"


def _bundle_manifest(path: str) -> tuple[str, dict] | None:
    """(bundle_dir, manifest) when ``path`` is an incident bundle — the
    bundle dir itself, its manifest.json, or a path whose parsed JSON
    carries the bundle format marker. None otherwise."""
    manifest_path = None
    if os.path.isdir(path):
        manifest_path = os.path.join(path, "manifest.json")
    elif os.path.basename(path) == "manifest.json":
        manifest_path = path
    if manifest_path is None or not os.path.isfile(manifest_path):
        return None
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    if manifest.get("format") != "fls-incident-bundle":
        return None
    return os.path.dirname(manifest_path) or ".", manifest


def load_manifest(path: str) -> dict:
    """Just the manifest of an incident bundle — the cheap form for
    ``incidents list``/``show``, which must not parse every bundle's
    multi-MB trace to print a one-line summary."""
    found = _bundle_manifest(path)
    if found is None:
        raise ValueError(f"{path} is not an incident bundle")
    return found[1]


def journal_tail_len(path: str) -> int:
    """Event count of a bundle's journal tail (line count — no JSON
    parse; the ``incidents list`` summary column)."""
    found = _bundle_manifest(path)
    if found is None:
        return 0
    try:
        with open(os.path.join(found[0], "journal_tail.jsonl")) as f:
            return sum(1 for line in f if line.strip())
    except OSError:
        return 0


def load_bundle(path: str) -> dict:
    """An incident bundle's parts: ``{"path", "manifest", "journal",
    "metrics", "config", "trace_events"}`` — missing files load as
    empty (a partially-captured bundle still renders)."""
    found = _bundle_manifest(path)
    if found is None:
        raise ValueError(f"{path} is not an incident bundle")
    bundle_dir, manifest = found

    def load_json(name: str, default):
        p = os.path.join(bundle_dir, name)
        try:
            with open(p) as f:
                if name.endswith(".jsonl"):
                    return [
                        json.loads(line)
                        for line in f.read().splitlines()
                        if line.strip()
                    ]
                return json.load(f)
        except (OSError, ValueError):
            return default

    trace_path = os.path.join(bundle_dir, "trace.json")
    try:
        trace_events = load_trace(trace_path)
    except (OSError, ValueError):
        trace_events = []
    return {
        "path": bundle_dir,
        "manifest": manifest,
        "journal": load_json("journal_tail.jsonl", []),
        "metrics": load_json("metrics.json", {}),
        "config": load_json("config.json", {}),
        "trace_events": trace_events,
    }


def load_trace(path: str) -> list[dict]:
    """Normalized event list from a Chrome trace JSON or a JSONL export:
    ``{"name", "cat", "ts_s", "dur_s"?, ...attrs}`` per event. Format is
    detected by parsing, not extension: a whole-file JSON document is the
    Chrome form; anything else is read line-by-line as JSONL. An
    incident-bundle directory (or its manifest.json) resolves to the
    bundle's embedded ``trace.json``."""
    found = _bundle_manifest(path)
    if found is not None:
        path = os.path.join(found[0], "trace.json")
    with open(path) as f:
        text = f.read()
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        pass
    if (
        doc is None
        or not isinstance(doc, (dict, list))
        or (isinstance(doc, dict) and "traceEvents" not in doc)
    ):
        # JSONL (including the one-line edge case, which parses as a
        # plain dict with no traceEvents key).
        return [
            json.loads(line) for line in text.splitlines() if line.strip()
        ]
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    out = []
    for ev in events:
        if ev.get("ph") not in ("X", "i"):
            continue
        d = {
            "name": ev.get("name", ""),
            "cat": ev.get("cat", ""),
            "ts_s": float(ev.get("ts", 0.0)) / 1e6,
        }
        if ev.get("ph") == "X":
            d["dur_s"] = float(ev.get("dur", 0.0)) / 1e6
        d.update(ev.get("args") or {})
        out.append(d)
    return out


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    """Total covered seconds of possibly-overlapping [start, end) spans."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def _quantiles(samples: list[float]) -> dict[str, float]:
    if not samples:
        return {"count": 0}
    xs = sorted(samples)

    def pct(p: float) -> float:
        # Nearest-rank on the sorted samples (no numpy dependency here:
        # the analyzer must run anywhere a trace file can land).
        i = min(len(xs) - 1, max(0, round(p / 100 * (len(xs) - 1))))
        return round(xs[i], 6)

    return {
        "count": len(xs),
        "mean": round(sum(xs) / len(xs), 6),
        "p50": pct(50),
        "p95": pct(95),
        "p99": pct(99),
        "max": round(xs[-1], 6),
    }


def analyze(events: list[dict]) -> dict:
    """The report dict (see module docstring) for a normalized event list."""
    spans = [e for e in events if "dur_s" in e]
    if not events:
        return {"events": 0}
    # Wall excludes the synthetic metadata records: the Chrome export's
    # trace_meta rides at ts=0 (tracer construction), which would anchor
    # the wall at process start and dilute link utilization — and make
    # the same ring report different numbers per export format.
    timed = [
        e for e in events if e["name"] not in ("trace_meta", "process_name")
    ] or events
    t0 = min(e["ts_s"] for e in timed)
    t1 = max(e["ts_s"] + e.get("dur_s", 0.0) for e in timed)
    wall = max(t1 - t0, 1e-9)

    by_name: dict[str, dict[str, float]] = {}
    for s in spans:
        d = by_name.setdefault(s["name"], {"count": 0, "total_s": 0.0})
        d["count"] += 1
        d["total_s"] += s["dur_s"]
    for d in by_name.values():
        d["total_s"] = round(d["total_s"], 6)
        d["mean_s"] = round(d["total_s"] / d["count"], 6)

    stream_busy = _union_seconds(
        [
            (s["ts_s"], s["ts_s"] + s["dur_s"])
            for s in spans
            if s["name"] in STREAM_SPAN_NAMES
        ]
    )
    produce_s = by_name.get(PRODUCE_SPAN, {}).get("total_s", 0.0)
    wait_s = by_name.get(WAIT_SPAN, {}).get("total_s", 0.0)

    # Per-sweep phase profile: spans correlated by sweep_id. The parent
    # "sweep" span is the per-sweep wall, not a phase — reported apart.
    sweeps: dict[int, dict[str, float]] = {}
    sweep_wall = 0.0
    for s in spans:
        sid = s.get("sweep_id")
        if sid is None:
            continue
        if s["name"] == "sweep":
            sweeps.setdefault(int(sid), {})
            sweep_wall += s["dur_s"]
            continue
        ph = sweeps.setdefault(int(sid), {})
        ph[s["name"]] = round(ph.get(s["name"], 0.0) + s["dur_s"], 6)
    phase_totals: dict[str, float] = {}
    for ph in sweeps.values():
        for name, sec in ph.items():
            phase_totals[name] = round(phase_totals.get(name, 0.0) + sec, 6)

    report = {
        "events": len(events),
        "spans": len(spans),
        "wall_s": round(wall, 6),
        "spans_by_name": {k: by_name[k] for k in sorted(by_name)},
        "stream_busy_s": round(stream_busy, 6),
        "link_utilization": round(stream_busy / wall, 4),
        "sweeps": len(sweeps),
        "sweep_wall_s": round(sweep_wall, 6),
        "sweep_phase_s": {k: phase_totals[k] for k in sorted(phase_totals)},
        "ttft_s": _quantiles(
            [
                float(e["seconds"])
                for e in events
                if e["name"] == "ttft" and "seconds" in e
            ]
        ),
        "token_latency_s": _quantiles(
            [
                float(e["seconds"])
                for e in events
                if e["name"] == "token_latency" and "seconds" in e
            ]
        ),
    }
    if produce_s > 0:
        report["overlap_efficiency"] = round(
            max(0.0, min(1.0, (produce_s - wait_s) / produce_s)), 4
        )
        report["source_wait_s"] = round(wait_s, 6)
        report["produce_s"] = round(produce_s, 6)
    drops = [e.get("trace_drops") for e in events if e["name"] == "trace_meta"]
    if drops and drops[-1] is not None:
        report["trace_drops"] = int(drops[-1])
    counts = {}
    for name in (
        "reread_heal", "quarantine", "spill_recompute", "io_retry",
        "engine_recovery", "wave_abort", "watchdog_stall", "wave_admit",
        "request_finish", "hostcache_hit", "hostcache_miss",
    ):
        n = sum(1 for e in events if e["name"] == name)
        if n:
            counts[name] = n
    if counts:
        report["event_counts"] = counts
    return report


def format_report(report: dict) -> str:
    lines = [
        f"trace: {report.get('events', 0)} events, "
        f"{report.get('spans', 0)} spans over "
        f"{report.get('wall_s', 0.0):.3f}s wall",
        f"link utilization: {report.get('link_utilization', 0.0):.1%} "
        f"(stream busy {report.get('stream_busy_s', 0.0):.3f}s)",
    ]
    if "overlap_efficiency" in report:
        lines.append(
            f"compute/stream overlap efficiency: "
            f"{report['overlap_efficiency']:.1%} "
            f"(source_wait {report['source_wait_s']:.3f}s of "
            f"{report['produce_s']:.3f}s produce)"
        )
    if report.get("sweeps"):
        lines.append(
            f"sweeps: {report['sweeps']} "
            f"({report.get('sweep_wall_s', 0.0):.3f}s sweep wall); "
            "per-phase totals:"
        )
        for name, sec in sorted(
            report.get("sweep_phase_s", {}).items(),
            key=lambda kv: -kv[1],
        ):
            lines.append(f"  {name:<16} {sec:.3f}s")
    for key, label in (
        ("ttft_s", "TTFT"),
        ("token_latency_s", "per-token latency"),
    ):
        q = report.get(key) or {}
        if q.get("count"):
            lines.append(
                f"{label}: n={q['count']} p50={q['p50']}s "
                f"p95={q['p95']}s p99={q['p99']}s"
            )
    if report.get("event_counts"):
        lines.append(
            "events: "
            + " ".join(
                f"{k}={v}" for k, v in sorted(report["event_counts"].items())
            )
        )
    if report.get("trace_drops"):
        lines.append(
            f"WARNING: ring overflow dropped {report['trace_drops']} oldest "
            "spans — raise the trace capacity for full-run timelines"
        )
    return "\n".join(lines)


def analyze_bundle(path: str) -> dict:
    """Structured incident report for one bundle: the manifest, journal
    event counts by kind/severity, the correlation-id surface (replicas,
    waves, requests the journal names), and the embedded trace's own
    analyzer report."""
    b = load_bundle(path)
    journal = b["journal"]
    by_kind: dict[str, int] = {}
    by_severity: dict[str, int] = {}
    replicas: set = set()
    waves: set = set()
    requests: set = set()
    for ev in journal:
        by_kind[ev.get("kind", "?")] = by_kind.get(ev.get("kind", "?"), 0) + 1
        sev = ev.get("severity", "?")
        by_severity[sev] = by_severity.get(sev, 0) + 1
        if ev.get("replica") is not None:
            replicas.add(ev["replica"])
        if ev.get("wave_id") is not None:
            waves.add(ev["wave_id"])
        for rid in ev.get("request_ids") or (
            [ev["request_id"]] if ev.get("request_id") is not None else []
        ):
            requests.add(rid)
    report = {
        "path": b["path"],
        "captured_at": b["manifest"].get("captured_at"),
        "trigger": b["manifest"].get("trigger", {}),
        "journal_events": len(journal),
        "events_by_kind": {k: by_kind[k] for k in sorted(by_kind)},
        "events_by_severity": {
            k: by_severity[k] for k in sorted(by_severity)
        },
        "replicas": sorted(replicas),
        "waves": sorted(waves),
        "requests": sorted(requests),
        "journal_health": b["manifest"].get("journal", {}),
        "timeline": journal,
    }
    if b["trace_events"]:
        report["trace_report"] = analyze(b["trace_events"])
    return report


def format_incident(report: dict) -> str:
    """Human timeline for one bundle (``cli incidents analyze``)."""
    trig = report.get("trigger", {})
    lines = [
        f"incident bundle: {report.get('path')}",
        f"captured: {report.get('captured_at')}  trigger: "
        f"{trig.get('kind')} (severity {trig.get('severity')}, "
        f"seq {trig.get('seq')})",
        "events: "
        + (
            " ".join(
                f"{k}={v}"
                for k, v in sorted(report.get("events_by_kind", {}).items())
            )
            or "(empty journal tail)"
        ),
    ]
    corr = []
    if report.get("replicas"):
        corr.append(f"replicas={report['replicas']}")
    if report.get("waves"):
        corr.append(f"waves={report['waves']}")
    if report.get("requests"):
        corr.append(f"requests={len(report['requests'])}")
    if corr:
        lines.append("correlation: " + " ".join(corr))
    health = report.get("journal_health", {})
    if health:
        lines.append(
            f"journal: written={health.get('events_written', 0)} "
            f"dropped={health.get('events_dropped', 0)} "
            f"rotations={health.get('rotations', 0)} "
            f"bundles={health.get('bundles', 0)} "
            f"debounces={health.get('debounces', 0)}"
        )
    lines.append("timeline:")
    t0 = None
    for ev in report.get("timeline", []):
        ts = ev.get("ts")
        if t0 is None and ts is not None:
            t0 = ts
        rel = f"+{ts - t0:8.3f}s" if ts is not None and t0 is not None else " " * 10
        extras = " ".join(
            f"{k}={v}"
            for k, v in ev.items()
            if k not in ("seq", "ts", "kind", "severity")
        )
        lines.append(
            f"  {rel}  #{ev.get('seq', '?'):>5} "
            f"[{ev.get('severity', '?'):>8}] {ev.get('kind', '?')}"
            + (f"  {extras}" if extras else "")
        )
    tr = report.get("trace_report")
    if tr:
        lines.append(
            f"trace: {tr.get('events', 0)} events over "
            f"{tr.get('wall_s', 0.0):.3f}s wall (load "
            f"{report.get('path')}/trace.json in Perfetto)"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="flexible-llm-sharding-tpu trace-report",
        description="Analyze a --trace recording: link utilization, "
        "compute/stream overlap efficiency, per-phase sweep breakdown, "
        "TTFT and per-token latency quantiles.",
    )
    p.add_argument("--trace", type=str, required=True,
                   help="trace file written by --trace_out (Chrome JSON "
                        "or JSONL), or an incident-bundle directory — "
                        "its embedded trace.json is analyzed")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as one JSON object on stdout")
    args = p.parse_args(argv)
    try:
        events = load_trace(args.trace)
    except (OSError, ValueError, KeyError) as e:
        print(f"trace-report: cannot read {args.trace}: {e!r}",
              file=sys.stderr)
        return 2
    report = analyze(events)
    if args.json:
        print(json.dumps(report))
    else:
        print(format_report(report))
    return 0


__all__ = [
    "analyze",
    "analyze_bundle",
    "format_incident",
    "format_report",
    "journal_tail_len",
    "load_bundle",
    "load_manifest",
    "load_trace",
    "main",
]
