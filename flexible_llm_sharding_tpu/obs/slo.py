"""Black-box flight recorder, part 3: SLO targets and error budgets.

PR 12 gave every request an SLO *class* and exported per-class TTFT /
latency quantiles — numbers with no contract behind them. This module
adds the contract: :class:`~flexible_llm_sharding_tpu.config.SLOConfig`
declares per-class p95 TTFT targets, an aggregate per-token-latency p95
target, and an availability target, and :class:`SLOTracker` turns the
existing ``ServingMetrics`` streams into **error-budget accounting**:

- A p95 target allows 5% of samples over the line by definition. The
  **burn rate** is ``violating_fraction / 0.05`` over the bounded
  recent-sample window — 1.0 means burning budget exactly at the
  allowed rate, 2.0 means at twice it; **budget remaining** is
  ``max(0, 1 - burn_rate)``.
- Availability compares the failed-request fraction against the allowed
  ``1 - availability_target`` the same way.

Everything exports as the ``fls_slo_*`` gauge family (pre-seeded for
all three classes, so "no samples yet" is scrapeable), and a class that
**exhausts** its budget (burn rate >= 1 with at least ``min_samples``
samples) emits an ``slo_budget_exhausted`` journal event — severity
``error``, so with the incident recorder armed at its default trigger,
burning through an error budget captures a bundle exactly like a crash
does. The exhaustion latch re-arms once the burn rate falls back below
0.5 (hysteresis against flapping at the boundary).

The tracker is pull-based: it reads the metrics windows at scrape /
stats-line time (plus a rate-limited per-sweep check), so the serving
hot path pays nothing for SLO accounting.
"""

from __future__ import annotations

import collections
import threading
import time

from flexible_llm_sharding_tpu.obs import events as obs_events

# A p95 target tolerates this fraction of samples over the line; the
# error budget is measured against it.
P95_ALLOWED_VIOLATION = 0.05
# Exhaustion latch re-arms below this burn rate (hysteresis).
REARM_BURN_RATE = 0.5
# Worst-burn observations kept for burn_rate_trend() (one per stats()
# evaluation — scrape / stats line / rate-limited sweep probe).
TREND_HISTORY = 32
# A windowed burn delta inside +/- this band reads as flat — scrape
# jitter must not register as a direction.
TREND_FLAT_BAND = 0.05


def _p95(samples: list[float]) -> float:
    if not samples:
        return 0.0
    xs = sorted(samples)
    i = min(len(xs) - 1, max(0, round(0.95 * (len(xs) - 1))))
    return round(xs[i], 4)


def _budget(samples: list[float], target: float) -> dict:
    """Burn rate + remaining budget of one p95 stream vs its target."""
    n = len(samples)
    if not target or not n:
        return {
            "target_s": target,
            "samples": n,
            "p95_s": _p95(samples),
            "burn_rate": 0.0,
            "budget_remaining": 1.0,
        }
    violations = sum(1 for s in samples if s > target)
    burn = (violations / n) / P95_ALLOWED_VIOLATION
    return {
        "target_s": target,
        "samples": n,
        "p95_s": _p95(samples),
        "burn_rate": round(burn, 4),
        "budget_remaining": round(max(0.0, 1.0 - burn), 4),
    }


class SLOTracker:
    """Compliance tracker over a ``ServingMetrics`` (module docstring).

    Registered as the ``slo`` registry source on every serving engine —
    the exposition carries ``fls_slo_ttft_<class>_burn_rate`` /
    ``_budget_remaining`` / ``_p95_s`` per class plus the aggregate
    token-latency and availability budgets, all pre-seeded."""

    def __init__(self, slo_cfg, metrics):
        self.cfg = slo_cfg
        self.metrics = metrics
        self._ttft_targets = (
            slo_cfg.ttft_target_map() if slo_cfg.enabled else {}
        )
        self._lock = threading.Lock()
        self._latched: set = set()  # guarded by: _lock
        self._last_check = 0.0  # guarded by: _lock
        self.budget_exhausted_events = 0  # guarded by: _lock
        # Worst burn rate per stats() evaluation, newest last — the
        # burn_rate_trend() window. guarded by: _lock
        self._burn_history: collections.deque = collections.deque(
            maxlen=TREND_HISTORY
        )

    # -- accounting --------------------------------------------------------

    def stats(self) -> dict:
        """The ``slo`` registry source. Computing the budgets IS the
        exhaustion check — scrapes, stats lines, and the engine's
        rate-limited per-sweep probe all share this one path, so the
        numbers an operator sees and the journal events agree by
        construction."""
        from flexible_llm_sharding_tpu.utils.metrics import SLO_CLASS_NAMES

        out: dict = {"enabled": int(self.cfg.enabled)}
        exhausted: list[tuple[str, dict]] = []
        ttft: dict = {}
        for cls in SLO_CLASS_NAMES:
            entry = _budget(
                self.metrics.ttft_class_samples(cls),
                self._ttft_targets.get(cls, 0.0),
            )
            ttft[cls] = entry
            self._judge(f"ttft:{cls}", entry, exhausted)
        out["ttft"] = ttft
        tok = _budget(
            self.metrics.token_latency_samples(),
            self.cfg.token_latency_p95_s if self.cfg.enabled else 0.0,
        )
        out["token_latency"] = tok
        self._judge("token_latency", tok, exhausted)
        out["availability"] = self._availability(exhausted)
        worst = max(
            [e["burn_rate"] for e in ttft.values()]
            + [tok["burn_rate"], out["availability"]["burn_rate"]]
        )
        out["worst_burn_rate"] = worst
        with self._lock:
            out["budget_exhausted_events"] = self.budget_exhausted_events
            self._burn_history.append(worst)
        out["trend"] = self.burn_rate_trend()
        for key, entry in exhausted:
            metric, _, cls = key.partition(":")
            obs_events.emit(
                "slo_budget_exhausted",
                metric=metric,
                slo_class=cls or None,
                burn_rate=entry.get("burn_rate"),
                target=entry.get("target_s", entry.get("target")),
                samples=entry.get("samples", entry.get("requests")),
            )
        return out

    def _availability(self, exhausted: list) -> dict:
        target = self.cfg.availability_target if self.cfg.enabled else 0.0
        completed = self.metrics.counter("completed")
        failed = self.metrics.counter("failed")
        total = completed + failed
        entry: dict = {
            "target": target,
            "requests": total,
            "observed": round(completed / total, 4) if total else 1.0,
            "burn_rate": 0.0,
            "budget_remaining": 1.0,
        }
        if target and total:
            allowed = max(1.0 - target, 1e-9)
            burn = (failed / total) / allowed
            entry["burn_rate"] = round(burn, 4)
            entry["budget_remaining"] = round(max(0.0, 1.0 - burn), 4)
        self._judge("availability", entry, exhausted)
        return entry

    def _judge(self, key: str, entry: dict, exhausted: list) -> None:
        """Latch-guarded exhaustion decision for one budget entry. The
        journal emit happens OUTSIDE the tracker lock (the caller
        drains ``exhausted``); the latch keeps a sustained burn from
        emitting once per scrape."""
        n = entry.get("samples", entry.get("requests", 0))
        burning = (
            entry["burn_rate"] >= 1.0 and n >= self.cfg.min_samples
        )
        with self._lock:
            if burning and key not in self._latched:
                self._latched.add(key)
                self.budget_exhausted_events += 1
                exhausted.append((key, entry))
            elif not burning and entry["burn_rate"] < REARM_BURN_RATE:
                self._latched.discard(key)

    def burn_rate_trend(self, k: int = 8) -> dict:
        """Windowed burn direction over the last ``k`` worst-burn
        observations (one per :meth:`stats` evaluation): the autoscaler's
        transient-spike filter — a single hot scrape reads flat until the
        burn SUSTAINS across the window. Pre-seeded numeric (rising /
        falling flags + signed delta) so the ``fls_slo_*`` family carries
        it before the first sample. ``delta`` is newest - oldest inside
        the window; a magnitude inside ``TREND_FLAT_BAND`` is flat."""
        with self._lock:
            window = list(self._burn_history)[-max(2, k):]
        delta = window[-1] - window[0] if len(window) >= 2 else 0.0
        return {
            "window": len(window),
            "burn_delta": round(delta, 4),
            "rising": int(delta > TREND_FLAT_BAND),
            "falling": int(delta < -TREND_FLAT_BAND),
        }

    # -- hot-path probe ----------------------------------------------------

    def maybe_check(self, interval_s: float = 1.0) -> None:
        """Per-sweep probe (engine ``_post_sweep``): evaluate budgets at
        most once per ``interval_s`` so exhaustion journals promptly on
        a busy server even when nothing scrapes the endpoint. Disabled
        SLOs return on one bool check."""
        if not self.cfg.enabled:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_check < interval_s:
                return
            self._last_check = now
        self.stats()


__all__ = [
    "P95_ALLOWED_VIOLATION",
    "REARM_BURN_RATE",
    "SLOTracker",
    "TREND_FLAT_BAND",
    "TREND_HISTORY",
]
