"""Unified observability: span tracing, the metrics registry, and the
trace analyzer.

Submodules (imported directly — this package root stays import-light so
hot-path modules can depend on it without cycles):

- ``obs.trace``    bounded-ring span tracer with correlation ids
  (sweep_id / shard_idx / wave_id / request_id), exported as Chrome
  trace-event JSON (Perfetto-loadable) or JSONL. Zero-cost no-op when
  disabled.
- ``obs.registry`` the process metrics registry every subsystem's
  counters register into, with Prometheus text exposition and an
  optional HTTP endpoint (the serve engine's ``--metrics_port``).
- ``obs.report``   the trace analyzer behind ``cli trace-report``:
  link utilization, compute/stream overlap efficiency, per-phase sweep
  breakdown, TTFT / per-token latency quantiles — plus the
  incident-bundle analyzer behind ``cli incidents``.
- ``obs.events``   the black-box flight recorder's durable append-only
  JSONL event journal (docs/incidents.md): every failure-path site
  writes through it; zero-cost no-op when disabled.
- ``obs.incident`` severity-triggered incident bundles: journal tail +
  metrics snapshot + trace ring + resolved config, debounced and
  disk-budgeted.
- ``obs.slo``      SLO targets + error budgets over the per-class
  latency streams, exported as the ``fls_slo_*`` gauge family.
"""
