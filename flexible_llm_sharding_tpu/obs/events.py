"""Black-box flight recorder, part 1: the durable event journal.

The trace ring (``obs/trace.py``) and the metrics registry
(``obs/registry.py``) answer *live* questions — but both are volatile:
when an engine recovers, a replica is hard-failed, or the process dies
under pressure, the counters and the ring die with it (or the ring's
drop-oldest policy has already evicted the interesting window). This
module is the durable third leg: a process-wide, thread-safe,
APPEND-ONLY JSONL journal that every failure-path site writes through —
engine recoveries and wave aborts, replica death/drain/re-dispatch,
quarantines and re-read heals, pressure ladder steps and hard resource
events, watchdog stalls, preemptions, SLO budget exhaustion. Each event
carries a monotonic ``seq``, a wall-clock ``ts``, its ``kind`` and
``severity``, and the same correlation ids the tracer uses
(``sweep_id`` / ``wave_id`` / ``request_id`` / ``replica``), so a
post-mortem stitches the journal, the trace export, and the metrics
snapshot back into one story.

Design constraints, in order (the tracer's, plus durability):

1. **Zero-cost when disabled.** ``emit()`` reads one bool and returns.
   The journal is compiled into every failure path; none of them may
   pay for it while it is off (the default).
2. **Never an engine error.** A journal write failure — ENOSPC, a
   yanked volume, an injected ``disk_full`` fault — degrades to a
   counted drop (``events_dropped``), never an exception into the
   failure path that was being recorded. A flight recorder that crashes
   the plane is worse than none.
3. **Bounded.** The file rotates atomically (``os.replace`` to
   ``journal.jsonl.1``) when it exceeds its byte budget; one previous
   generation is kept. A bounded in-memory ring of the newest events
   backs the incident recorder's journal tail even while disk writes
   are failing.
4. **Machine-checked vocabulary.** Every ``kind`` emitted anywhere must
   be declared in :data:`EVENT_KINDS` below and documented in
   ``docs/incidents.md`` — flscheck's EVENT-REG rule (analysis/rules.py)
   enforces it, exactly as SITE-REG does for fault sites.

The process-wide singleton is :data:`JOURNAL`; the CLIs and engines
enable it from ``FrameworkConfig.journal_dir`` / ``incidents_dir`` via
:func:`ensure_configured`. Its health (events written/dropped,
rotations, and the incident recorder's bundle counters) is a process
registry source -> the ``fls_journal_*`` Prometheus family.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

# The central kinds table: kind -> severity. Machine-checked (EVENT-REG):
# every `emit("<kind>", ...)` literal in the package must be declared
# here AND documented in docs/incidents.md's kinds table, and every
# declared kind must actually be emitted somewhere. Severities order
# info < warning < error < critical; the incident recorder triggers at
# FrameworkConfig.incident_trigger and above.
EVENT_KINDS = {
    # serving engine (serve/engine.py)
    "engine_recovery": "error",      # degrade-don't-die: source restarted
    "engine_fatal": "critical",      # the loop died; every future failed
    "wave_abort": "error",           # one in-flight wave failed mid-sweep
    "wave_reject": "warning",        # a wave failed at tokenization/init
    "watchdog_stall": "error",       # sweep made no progress; source aborted
    "wave_preempt": "info",          # scheduler retired a best-effort wave
    "adapter_reject": "warning",     # unknown/corrupt LoRA adapter: that
                                     # tenant's requests failed typed at
                                     # wave assembly (base unaffected)
    # replica fleet (serve/fleet.py)
    "replica_dead": "critical",      # hard-fail: engine-fatal or stalled
    "replica_drain": "warning",      # graceful drain started
    "replica_recycled": "info",      # fresh engine seated in the slot
    "redispatch": "warning",         # orphan re-dispatched to a survivor
    # integrity (runtime/executor.py, runtime/activations.py)
    "reread_heal": "warning",        # checksum mismatch healed by re-read
    "quarantine": "critical",        # on-disk corruption; path quarantined
    "spill_recompute": "warning",    # spill corrupt; block recomputed
    # resource pressure (runtime/pressure.py)
    "pressure_step": "warning",      # brownout ladder moved up or down
    "pressure_event": "error",       # hard resource event (OOM / ENOSPC)
    # SLO error budgets (obs/slo.py)
    "slo_budget_exhausted": "error",  # a class burned its error budget
    # adaptive speculation controller (serve/spec.py)
    "spec_k_raise": "info",          # windowed acceptance earned a class +1 k
    "spec_k_backoff": "info",        # k shrank: low acceptance or pressure
    # fleet autoscaler (serve/autoscale.py)
    "autoscale_grow": "info",        # controller added a replica
    "autoscale_shrink": "info",      # controller started a graceful drain
    "autoscale_blocked": "warning",  # a wanted action hit an interlock
    # the incident recorder itself (obs/incident.py)
    "incident_capture": "info",      # a bundle landed on disk
    # crash-safe serving (serve/wal.py, serve/recovery.py, serve/engine.py)
    "wal_torn_tail": "warning",      # partial tail record truncated at scan
    "wal_replay": "warning",         # warm restart re-admitted open requests
    "shutdown_drain": "info",        # graceful restart drained at boundary
}

# Severity lattice (index = rank). severity_rank("critical") == 3.
SEVERITY_LEVELS = ("info", "warning", "error", "critical")


def severity_rank(severity: str) -> int:
    """Rank of a severity name. Unknown names rank ABOVE 'critical' —
    the fail-safe direction for a TRIGGER THRESHOLD (a typo'd trigger
    captures nothing rather than everything; config validation rejects
    typos on the CLI path anyway). Callers comparing an EVENT's
    severity against a threshold must reject unknown event severities
    explicitly (``severity in SEVERITY_LEVELS``) instead of leaning on
    this rank — the recorder's ``observe`` does."""
    try:
        return SEVERITY_LEVELS.index(severity)
    except ValueError:
        return len(SEVERITY_LEVELS)


JOURNAL_FILE = "journal.jsonl"


class EventJournal:
    """Process-wide append-only JSONL event journal (module docstring).

    ``record()`` serializes one event under the journal lock (seq order
    and rotation atomicity both require it; the write is one short line
    on a rare failure path), appends it to the bounded in-memory ring,
    and — outside the lock — hands it to the attached incident recorder.
    """

    DEFAULT_TAIL_EVENTS = 1024

    def __init__(self, tail_events: int = DEFAULT_TAIL_EVENTS):
        self._lock = threading.Lock()
        self.enabled = False
        self.path = ""  # journal file ("" = ring-only, no durability)
        self._max_bytes = 0
        self._file = None  # guarded by: _lock
        self._bytes_current = 0  # guarded by: _lock
        self._seq = 0  # guarded by: _lock
        self._ring: deque = deque(maxlen=tail_events)  # guarded by: _lock
        self._injector = None  # chaos: fires the disk_full site per write
        self._recorder = None  # obs/incident.py IncidentRecorder
        # Counters (all exported via stats(); COUNTER-EXPORT audited).
        self.events_written = 0  # guarded by: _lock
        self.events_dropped = 0  # guarded by: _lock
        self.rotations = 0  # guarded by: _lock

    # -- lifecycle ---------------------------------------------------------

    def configure(
        self,
        journal_dir: str,
        max_bytes: int = 16_000_000,
        injector=None,
    ) -> "EventJournal":
        """Enable the journal writing ``<journal_dir>/journal.jsonl``.
        Idempotent for the same directory; a second configure with a
        different directory keeps the first (process-singleton
        precedent: first config wins). Registers the ``journal`` source
        in the process metrics registry."""
        # flscheck: disable=LOCK-IO: one-time journal-file open under the configure lock — a racing configure must not open two generations of the same append-only file
        with self._lock:
            if self._file is None and journal_dir:
                os.makedirs(journal_dir, exist_ok=True)
                self.path = os.path.join(journal_dir, JOURNAL_FILE)
                self._max_bytes = int(max_bytes)
                try:
                    self._file = open(self.path, "a")
                    self._bytes_current = self._file.tell()
                except OSError:
                    # An unwritable journal dir degrades to ring-only —
                    # pillar 2: never an engine error.
                    self._file = None
                    self.events_dropped += 1
            if injector is not None and self._injector is None:
                self._injector = injector
            self.enabled = True
        # Registry citizenship, the tracer's lazy-import precedent.
        from flexible_llm_sharding_tpu.obs.registry import REGISTRY

        REGISTRY.register("journal", self.stats)
        return self

    def attach_recorder(self, recorder) -> None:
        """Attach the incident recorder (first wins — one recorder per
        process, the controller_for precedent)."""
        with self._lock:
            if self._recorder is None:
                self._recorder = recorder

    @property
    def recorder(self):
        return self._recorder

    def close(self) -> None:
        """Disable and drop state (tests; a real process keeps its
        journal for life). Leaves the file on disk."""
        with self._lock:
            self.enabled = False
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
            self._file = None
            self.path = ""
            self._ring.clear()
            self._seq = 0
            self._bytes_current = 0
            self._injector = None
            self._recorder = None
            self.events_written = 0
            self.events_dropped = 0
            self.rotations = 0
        from flexible_llm_sharding_tpu.obs.registry import REGISTRY

        REGISTRY.unregister("journal")

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, fields: dict) -> None:
        """Append one event. Unknown kinds count as drops (EVENT-REG
        catches the literal statically; at runtime the failure path must
        not raise). Write failures count as drops; the ring still holds
        the event so an incident bundle's tail survives a full disk."""
        severity = EVENT_KINDS.get(kind)
        rec = None
        # flscheck: disable=LOCK-IO: the journal IS the serialized write path — monotonic seq order and atomic rotation both require the one-line append under the lock, and every caller is a rare failure path
        with self._lock:
            if severity is None:
                self.events_dropped += 1
                return
            self._seq += 1
            ev = {
                "seq": self._seq,
                "ts": round(time.time(), 6),
                "kind": kind,
                "severity": severity,
            }
            for k, v in fields.items():
                ev.setdefault(k, v)
            self._ring.append(ev)
            if self._file is not None:
                try:
                    if self._injector is not None:
                        # Chaos: the journal's own durability is a disk
                        # write like any spill — the existing disk_full
                        # site proves a full disk degrades to counted
                        # drops, never an engine error.
                        self._injector.fire("disk_full", detail=f"journal:{kind}")
                    line = json.dumps(ev, default=str) + "\n"
                    self._file.write(line)
                    self._file.flush()
                    self._bytes_current += len(line)
                    self.events_written += 1
                    if self._bytes_current >= self._max_bytes:
                        self._rotate_locked()
                except OSError:
                    self.events_dropped += 1
            else:
                self.events_dropped += 1
            rec = self._recorder
        if rec is not None:
            # Outside the journal lock: a capture walks the registry and
            # writes files; it must never stall concurrent emits.
            rec.observe(ev)

    def _rotate_locked(self) -> None:
        """Atomic size rotation (caller holds the lock): the live file
        becomes ``journal.jsonl.1`` via ``os.replace`` (atomic on POSIX)
        and a fresh generation opens. One previous generation is kept —
        the tail window an incident needs, bounded at 2x max_bytes."""
        try:
            self._file.close()
            os.replace(self.path, self.path + ".1")
            self._file = open(self.path, "a")
            self._bytes_current = 0
            self.rotations += 1
        except OSError:
            # Rotation failed (e.g. ENOSPC renaming): keep appending to
            # the oversized file rather than losing events.
            self.events_dropped += 1
            if self._file is None or self._file.closed:
                try:
                    self._file = open(self.path, "a")
                except OSError:
                    self._file = None

    # -- reads -------------------------------------------------------------

    def tail(self, n: int | None = None) -> list[dict]:
        """The newest ``n`` events (default: the whole ring), oldest
        first — served from the in-memory ring so it works even while
        disk writes are failing (the incident recorder's tail source)."""
        with self._lock:
            events = list(self._ring)
        return events if n is None else events[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The ``journal`` registry source (-> ``fls_journal_*``):
        journal health plus the incident recorder's bundle counters,
        pre-seeded to 0 so "no incidents" is scrapeable."""
        with self._lock:
            out = {
                "enabled": int(self.enabled),
                "seq": self._seq,
                "events_written": self.events_written,
                "events_dropped": self.events_dropped,
                "rotations": self.rotations,
                "bytes_current": self._bytes_current,
            }
            rec = self._recorder
        if rec is not None:
            out.update(rec.stats())
        else:
            out.update(
                {
                    "bundles": 0,
                    "debounces": 0,
                    "bundle_evictions": 0,
                    "bundle_errors": 0,
                }
            )
        return out


JOURNAL = EventJournal()


def emit(kind: str, **fields) -> None:
    """Module-level journal emit (the failure-path form): one bool check
    and a return while the journal is disabled — the whole disabled-path
    cost, mirroring ``obs.trace.instant``."""
    if JOURNAL.enabled:
        JOURNAL.record(kind, fields)


def enabled() -> bool:
    return JOURNAL.enabled


def ensure_configured(cfg) -> None:
    """Enable the process journal when the config asks for it
    (``cfg.journal_dir``, or ``cfg.incidents_dir`` — a flight recorder
    without a journal dir keeps its journal beside the bundles). Never
    disables — the journal is process-scoped, and a second engine with
    journaling off must not cut a live recording short. Under fault
    injection the journal carries its own injector instance so the
    ``disk_full`` site exercises the counted-drop degrade path with an
    independent deterministic schedule."""
    journal_dir = getattr(cfg, "journal_dir", "") or ""
    if not journal_dir:
        journal_dir = getattr(cfg, "incidents_dir", "") or ""
    if not journal_dir or JOURNAL.enabled:
        return
    injector = None
    faults = getattr(cfg, "faults", None)
    if faults is not None and getattr(faults, "enabled", False):
        from flexible_llm_sharding_tpu.faults.inject import FaultInjector

        injector = FaultInjector.from_config(faults)
    JOURNAL.configure(
        journal_dir,
        max_bytes=int(getattr(cfg, "journal_max_mb", 16.0) * 1e6),
        injector=injector,
    )


def reset_journal() -> None:
    """Close and reset the process journal (tests)."""
    JOURNAL.close()


__all__ = [
    "EVENT_KINDS",
    "EventJournal",
    "JOURNAL",
    "JOURNAL_FILE",
    "SEVERITY_LEVELS",
    "emit",
    "enabled",
    "ensure_configured",
    "reset_journal",
    "severity_rank",
]
