"""Black-box flight recorder, part 2: incident bundles.

When a failure crosses the configured severity (``incident_trigger``),
this module captures a **self-contained bundle directory** — everything
a post-mortem needs, frozen at the moment of failure:

- ``journal_tail.jsonl``  the newest journal events (served from the
  in-memory ring, so a full disk that is dropping journal writes still
  yields a tail)
- ``metrics.json``        the full process ``MetricsRegistry.collect()``
  snapshot (a raising source appears as its ``collect_error`` marker —
  preserved, never dropped: "this source was broken at capture time" is
  itself evidence)
- ``trace.json``          the live trace ring as Chrome trace-event JSON
  (Perfetto-loadable; empty when tracing is off)
- ``config.json``         the resolved FrameworkConfig/ServeConfig the
  process was running
- ``manifest.json``       the trigger event, capture time, file list,
  and journal health counters

Bundles land under a disk-budgeted directory (``incidents_max_mb``);
oldest bundles are evicted first. Two storm controls keep a failure
storm from yielding hundreds of bundles:

- **settle**: the capture waits ``incident_settle_s`` after the trigger,
  and every further trigger-severity event pushes the deadline out
  (bounded), so the whole storm — replica death, orphan re-dispatch,
  recycle — lands INSIDE the one bundle instead of after its snapshot;
- **debounce**: after a capture, further triggers within
  ``incident_debounce_s`` only count (``debounces``), they do not
  capture.

Bundle capture is best-effort end to end: any capture failure counts
(``bundle_errors``) and never raises into the failure path that
triggered it. Render a bundle with ``cli incidents analyze <dir>``
(obs/report.py) or load its ``trace.json`` in Perfetto directly.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time

from flexible_llm_sharding_tpu.obs import events as obs_events

BUNDLE_FORMAT = "fls-incident-bundle"
BUNDLE_VERSION = 1
MANIFEST_NAME = "manifest.json"
# How far a storm can push the settle deadline past the first trigger.
MAX_SETTLE_EXTENSION = 10.0


class IncidentRecorder:
    """Severity-triggered bundle capture over the process journal
    (module docstring). Attached to :data:`obs.events.JOURNAL`; its
    counters ride the ``fls_journal_*`` family."""

    def __init__(
        self,
        out_dir: str,
        max_bytes: int = 256_000_000,
        trigger: str = "error",
        debounce_s: float = 60.0,
        settle_s: float = 1.0,
        config_snapshot: dict | None = None,
    ):
        self.out_dir = out_dir
        self.max_bytes = int(max_bytes)
        self.trigger_rank = obs_events.severity_rank(trigger)
        self.debounce_s = float(debounce_s)
        self.settle_s = float(settle_s)
        self.config_snapshot = config_snapshot or {}
        self._lock = threading.Lock()
        self._pending = False  # guarded by: _lock
        self._deadline = 0.0  # guarded by: _lock
        self._pending_t0 = 0.0  # guarded by: _lock
        self._last_capture: float | None = None  # guarded by: _lock
        # Counters (exported via stats(); COUNTER-EXPORT audited).
        self.bundles = 0
        self.debounces = 0
        self.bundle_evictions = 0
        self.bundle_errors = 0

    # -- journal hook ------------------------------------------------------

    def observe(self, event: dict) -> None:
        """Journal-side hook, called for EVERY recorded event (off the
        journal lock). Sub-trigger severities return on one comparison;
        the recorder's own ``incident_capture`` marker is ignored so a
        capture can never re-trigger itself."""
        if event.get("kind") == "incident_capture":
            return
        severity = event.get("severity", "")
        if severity not in obs_events.SEVERITY_LEVELS:
            # An unknown event severity must never trigger: the rank
            # helper deliberately ranks unknowns ABOVE critical (the
            # fail-safe direction for thresholds), which is exactly the
            # wrong direction for an event-side comparison.
            return
        if obs_events.severity_rank(severity) < self.trigger_rank:
            return
        now = time.monotonic()
        with self._lock:
            if self._pending:
                # Storm extension: each further trigger event pushes the
                # capture out so the whole storm lands in the bundle's
                # journal tail — bounded, so a sustained storm still
                # yields a bundle rather than deferring forever.
                self._deadline = min(
                    now + self.settle_s,
                    self._pending_t0 + self.settle_s + MAX_SETTLE_EXTENSION,
                )
                return
            if (
                self._last_capture is not None
                and now - self._last_capture < self.debounce_s
            ):
                self.debounces += 1
                return
            self._pending = True
            self._pending_t0 = now
            self._deadline = now + self.settle_s
        if self.settle_s <= 0:
            self._settle_and_capture(event)
        else:
            threading.Thread(
                target=self._settle_and_capture,
                args=(event,),
                name="incident-capture",
                daemon=True,
            ).start()

    def _settle_and_capture(self, trigger_event: dict) -> None:
        while True:
            with self._lock:
                remaining = self._deadline - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(remaining, 0.05))
        path = None
        try:
            path = self.capture(trigger_event)
        finally:
            with self._lock:
                self._pending = False
                self._last_capture = time.monotonic()
        if path is not None:
            obs_events.emit(
                "incident_capture",
                bundle=os.path.basename(path),
                trigger=trigger_event.get("kind"),
                trigger_seq=trigger_event.get("seq"),
            )

    # -- capture -----------------------------------------------------------

    def capture(self, trigger_event: dict | None = None) -> str | None:
        """Write one bundle now (also the manual/CLI form). Returns the
        bundle path, or None on failure (counted, never raised)."""
        trigger_event = trigger_event or {"kind": "manual", "seq": 0}
        name = (
            f"incident-{int(trigger_event.get('seq') or 0):08d}-"
            f"{trigger_event.get('kind', 'manual')}"
        )
        final = os.path.join(self.out_dir, name)
        tmp = final + ".tmp"
        try:
            files = self._write_bundle(tmp, trigger_event)
            self._write_manifest(tmp, trigger_event, files)
            if os.path.isdir(final):
                shutil.rmtree(final)
            # Atomic publish: a bundle directory either carries its
            # manifest or does not exist under its final name — readers
            # (the CLI, the CI artifact upload) never see a half-bundle.
            os.replace(tmp, final)
            self.bundles += 1
        except Exception:  # noqa: BLE001 — flight-recorder pillar 2
            # Best-effort by contract: a capture failure (disk full, a
            # source torn down mid-walk) must never raise into the
            # failure path that triggered it. The drop is counted and
            # scrapeable (fls_journal_bundle_errors).
            self.bundle_errors += 1
            shutil.rmtree(tmp, ignore_errors=True)
            return None
        self._enforce_budget(keep=name)
        return final

    def _write_bundle(self, bundle_dir: str, trigger_event: dict) -> list[str]:
        from flexible_llm_sharding_tpu.obs.registry import REGISTRY
        from flexible_llm_sharding_tpu.obs.trace import TRACER

        os.makedirs(bundle_dir, exist_ok=True)
        files: list[str] = []

        def write(fname: str, payload) -> None:
            with open(os.path.join(bundle_dir, fname), "w") as f:
                if fname.endswith(".jsonl"):
                    for item in payload:
                        f.write(json.dumps(item, default=str) + "\n")
                else:
                    json.dump(payload, f, indent=1, default=str)
            files.append(fname)

        write("journal_tail.jsonl", obs_events.JOURNAL.tail())
        # collect() preserves a raising source as {"collect_error": 1} —
        # the bundle keeps that marker verbatim (a broken source at
        # capture time is evidence, not noise; pinned by test).
        write("metrics.json", REGISTRY.collect())
        write(
            "trace.json",
            {"traceEvents": TRACER.chrome_events(), "displayTimeUnit": "ms"},
        )
        write("config.json", self.config_snapshot)
        return files

    def _write_manifest(
        self, bundle_dir: str, trigger_event: dict, files: list[str]
    ) -> None:
        manifest = {
            "format": BUNDLE_FORMAT,
            "version": BUNDLE_VERSION,
            "captured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "pid": os.getpid(),
            "trigger": trigger_event,
            "files": sorted(files),
            "journal": obs_events.JOURNAL.stats(),
        }
        try:
            # Paged prefix-KV pool state (runtime/kvpool.py): counters +
            # a bounded page-table summary per pool, so a KV-related
            # failure shows what the pool held and shared post-mortem.
            # Best-effort like every bundle source; {} when no pool runs.
            from flexible_llm_sharding_tpu.runtime import kvpool

            if kvpool.process_pools():
                manifest["kvpool"] = kvpool.process_summary()
        except Exception:  # noqa: BLE001 — flight-recorder pillar 2
            manifest["kvpool"] = {"collect_error": 1}
        with open(os.path.join(bundle_dir, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=1, default=str)

    # -- disk budget -------------------------------------------------------

    def _bundle_dirs(self) -> list[str]:
        try:
            names = sorted(os.listdir(self.out_dir))
        except OSError:
            return []
        return [
            n
            for n in names
            if n.startswith("incident-")
            and not n.endswith(".tmp")
            and os.path.isdir(os.path.join(self.out_dir, n))
        ]

    @staticmethod
    def _dir_bytes(path: str) -> int:
        total = 0
        for root, _dirs, fnames in os.walk(path):
            for fname in fnames:
                try:
                    total += os.path.getsize(os.path.join(root, fname))
                except OSError:
                    pass
        return total

    def _enforce_budget(self, keep: str) -> None:
        """Evict oldest-first (bundle names sort by trigger seq) until
        the incidents dir fits the byte budget; the newest bundle is
        never evicted, whatever its size."""
        try:
            names = self._bundle_dirs()
            sizes = {
                n: self._dir_bytes(os.path.join(self.out_dir, n))
                for n in names
            }
            total = sum(sizes.values())
            for n in names:
                if total <= self.max_bytes or n == keep:
                    continue
                shutil.rmtree(
                    os.path.join(self.out_dir, n), ignore_errors=True
                )
                total -= sizes[n]
                self.bundle_evictions += 1
        except OSError:
            self.bundle_errors += 1

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Bundle counters, merged into the ``journal`` registry source
        by :meth:`obs.events.EventJournal.stats`."""
        return {
            "bundles": self.bundles,
            "debounces": self.debounces,
            "bundle_evictions": self.bundle_evictions,
            "bundle_errors": self.bundle_errors,
        }


def config_snapshot(cfg, serve_cfg=None) -> dict:
    """JSON-ready resolved-config dict for the bundle's config.json."""
    out: dict = {}
    if cfg is not None:
        out["framework"] = dataclasses.asdict(cfg)
    if serve_cfg is not None:
        out["serve"] = dataclasses.asdict(serve_cfg)
    return out


def ensure_configured(cfg, serve_cfg=None) -> IncidentRecorder | None:
    """Arm the incident recorder when ``cfg.incidents_dir`` is set
    (first caller wins; later engines share it — the process-singleton
    precedent). Also ensures the journal is enabled — bundles without a
    journal tail would be snapshots, not a flight recording."""
    # Journal first, unconditionally: a journal-only config (journal_dir
    # set, incidents_dir empty) must still arm the journal through this
    # one entry point — the kv_cache batch path reaches no other
    # ensure_configured call.
    obs_events.ensure_configured(cfg)
    incidents_dir = getattr(cfg, "incidents_dir", "") or ""
    if not incidents_dir:
        return obs_events.JOURNAL.recorder
    if obs_events.JOURNAL.recorder is None:
        os.makedirs(incidents_dir, exist_ok=True)
        recorder = IncidentRecorder(
            incidents_dir,
            max_bytes=int(getattr(cfg, "incidents_max_mb", 256.0) * 1e6),
            trigger=getattr(cfg, "incident_trigger", "error"),
            debounce_s=getattr(cfg, "incident_debounce_s", 60.0),
            settle_s=getattr(cfg, "incident_settle_s", 1.0),
            config_snapshot=config_snapshot(cfg, serve_cfg),
        )
        obs_events.JOURNAL.attach_recorder(recorder)
    return obs_events.JOURNAL.recorder


__all__ = [
    "BUNDLE_FORMAT",
    "IncidentRecorder",
    "MANIFEST_NAME",
    "config_snapshot",
    "ensure_configured",
]
