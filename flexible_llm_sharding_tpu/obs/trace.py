"""Sweep-timeline span tracer: a thread-safe bounded ring of timed spans.

The architecture's defining cost is that every decode step streams the
whole model through the chip, so the questions that matter are *timeline*
questions — is compute hidden under the host->HBM stream, where does a
sweep's wall time go, when did a wave join and when did its first token
land. This module records exactly that timeline: the executor's
producer/consumer, the host shard cache, the residency tier, the retry/
heal layer, and the serve wave lifecycle all emit spans here, correlated
by ``sweep_id`` / ``shard_idx`` / ``wave_id`` / ``request_id``.

Design constraints, in order:

1. **Zero-cost when disabled.** Every emit goes through a module-level
   helper that reads one bool and returns a shared no-op; no allocation,
   no lock, no timestamp is taken on the disabled path. Tracing must be
   safe to leave compiled into every hot loop.
2. **Bounded.** Spans land in a ring of ``capacity`` records; overflow
   drops the OLDEST spans and counts them (``trace_drops`` in
   ``stats()``), so a long-running server keeps the newest window and
   the loss is visible, never silent.
3. **Machine-readable.** ``write()`` exports Chrome trace-event JSON
   (load it at https://ui.perfetto.dev) or JSONL (one span per line, for
   ``cli trace-report`` and ad-hoc jq), chosen by file extension.

The process-wide singleton is ``TRACER``; the CLIs enable it from
``--trace`` via ``ensure_configured(cfg)`` and export via
``write_configured()``. Library users call ``TRACER.enable()`` directly.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque

# Correlation-id wells. A sweep id is unique per process (offline: one
# executor call's full pass over the shards; serving: one engine sweep),
# so spans from interleaved subsystems stitch back into one timeline.
_SWEEP_IDS = itertools.count(1)


def new_sweep_id() -> int:
    return next(_SWEEP_IDS)


class _NullSpan:
    """Shared no-op context manager returned by every emit while tracing
    is disabled — the whole disabled-path cost is one attribute read and
    one bool test in ``span()``."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live timed span; records itself into the tracer ring on exit."""

    __slots__ = ("_tracer", "name", "cat", "attrs", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.attrs = attrs

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        self._tracer._append(
            (self.name, self.cat, self._t0, t1 - self._t0,
             threading.get_ident(), self.attrs)
        )
        return False


class Tracer:
    """Thread-safe bounded-ring span recorder (see module docstring).

    Records are ``(name, cat, t_start_perf, dur_s | None, tid, attrs)``
    tuples; ``dur_s is None`` marks an instant event. Timestamps are
    ``time.perf_counter()`` values; ``epoch_offset`` maps them back to
    wall-clock for the exports.
    """

    DEFAULT_CAPACITY = 200_000

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self.capacity = int(capacity)
        self._ring: deque = deque()  # guarded by: _lock
        self.drops = 0  # oldest spans dropped on ring overflow  # guarded by: _lock
        self.enabled = False
        self.default_out: str = ""
        # perf_counter -> wall-clock epoch mapping, captured once so every
        # exported timestamp shares one base.
        self._perf0 = time.perf_counter()
        self._epoch0 = time.time()

    # -- recording ---------------------------------------------------------

    def _append(self, rec: tuple) -> None:
        with self._lock:
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self.drops += 1
            self._ring.append(rec)

    def span(self, name: str, cat: str = "runtime", **attrs):
        """Timed span context manager; no-op (shared object) when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, attrs)

    def instant(self, name: str, cat: str = "runtime", **attrs) -> None:
        """Zero-duration structured event (heals, stalls, wave admits)."""
        if not self.enabled:
            return
        self._append(
            (name, cat, time.perf_counter(), None, threading.get_ident(),
             attrs)
        )

    def complete(
        self, name: str, cat: str, t0_perf: float, dur_s: float, **attrs
    ) -> None:
        """Record an already-measured span (perf_counter start + duration)
        — for call sites that only know AFTER the fact whether the timed
        region should appear in the trace (e.g. a source wait that turned
        out to belong to a resume-skipped shard)."""
        if not self.enabled:
            return
        self._append(
            (name, cat, t0_perf, dur_s, threading.get_ident(), attrs)
        )

    # -- lifecycle ---------------------------------------------------------

    def enable(self, capacity: int | None = None) -> "Tracer":
        with self._lock:
            if capacity is not None:
                self.capacity = int(capacity)
            self.enabled = True
        # The tracer's own counters are registry citizens like every other
        # subsystem's (lazy import: registry must stay importable first).
        from flexible_llm_sharding_tpu.obs.registry import REGISTRY

        REGISTRY.register("trace", self.stats)
        return self

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.drops = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- observability -----------------------------------------------------

    def stats(self) -> dict[str, float]:
        with self._lock:
            out = {
                "trace_enabled": int(self.enabled),
                "trace_spans": len(self._ring),
                "trace_drops": self.drops,
            }
            if self.enabled:
                # Capacity only while recording: an all-zero snapshot keeps
                # the serve stats line free of a dead "trace" block.
                out["trace_capacity"] = self.capacity
            return out

    def snapshot(self) -> list[dict]:
        """The ring as a list of span dicts (oldest first), timestamps in
        epoch seconds. ``dur_s`` absent marks an instant event."""
        with self._lock:
            ring = list(self._ring)
            epoch0, perf0 = self._epoch0, self._perf0
        out = []
        for name, cat, t0, dur, tid, attrs in ring:
            d = {
                "name": name,
                "cat": cat,
                "ts_s": round(epoch0 + (t0 - perf0), 6),
                "tid": tid,
            }
            if dur is not None:
                d["dur_s"] = round(dur, 6)
            if attrs:
                d.update(attrs)
            out.append(d)
        return out

    # -- exports -----------------------------------------------------------

    def chrome_events(self) -> list[dict]:
        """Chrome trace-event list (Perfetto-loadable): complete ("X")
        events for spans, instant ("i") events for point events, plus one
        metadata record carrying the drop count."""
        with self._lock:
            ring = list(self._ring)
            perf0 = self._perf0
            drops = self.drops
        pid = os.getpid()
        events: list[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": "flexible-llm-sharding-tpu"},
            },
            {
                "name": "trace_meta",
                "ph": "i",
                "s": "g",
                "ts": 0,
                "pid": pid,
                "tid": 0,
                "args": {"trace_drops": drops},
            },
        ]
        for name, cat, t0, dur, tid, attrs in ring:
            ev = {
                "name": name,
                "cat": cat,
                "ts": round((t0 - perf0) * 1e6, 1),  # microseconds
                "pid": pid,
                "tid": tid,
                "args": attrs or {},
            }
            if dur is None:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = round(dur * 1e6, 1)
            events.append(ev)
        return events

    def write(self, path: str) -> str:
        """Export the ring: ``*.jsonl`` -> one span dict per line plus a
        trailing ``trace_meta`` record carrying the ring drop count (the
        Chrome export embeds the same record), so an overflowed —
        truncated — timeline is detectable in either format; anything
        else -> Chrome trace-event JSON."""
        if path.endswith(".jsonl"):
            spans = self.snapshot()
            with self._lock:
                drops = self.drops
            meta = {
                "name": "trace_meta",
                "cat": "meta",
                "ts_s": spans[0]["ts_s"] if spans else round(self._epoch0, 6),
                "trace_drops": drops,
            }
            with open(path, "w") as f:
                for s in spans:
                    f.write(json.dumps(s) + "\n")
                f.write(json.dumps(meta) + "\n")
        else:
            payload = {
                "traceEvents": self.chrome_events(),
                "displayTimeUnit": "ms",
            }
            with open(path, "w") as f:
                json.dump(payload, f)
        return path


TRACER = Tracer()


def span(name: str, cat: str = "runtime", **attrs):
    """Module-level emit against the process tracer (the hot-path form)."""
    if not TRACER.enabled:
        return _NULL_SPAN
    return _Span(TRACER, name, cat, attrs)


def instant(name: str, cat: str = "runtime", **attrs) -> None:
    if TRACER.enabled:
        TRACER.instant(name, cat, **attrs)


def enabled() -> bool:
    return TRACER.enabled


def ensure_configured(cfg) -> None:
    """Enable the process tracer when the config asks for it
    (``cfg.trace``); never disables — tracing is process-scoped and a
    second executor with trace off must not cut a live recording short.
    Remembers ``cfg.trace_out`` as the default export path."""
    if getattr(cfg, "trace", False):
        out = getattr(cfg, "trace_out", "") or ""
        if out:
            TRACER.default_out = out
        if not TRACER.enabled:
            TRACER.enable()


def write_configured(default: str = "fls_trace.json") -> str | None:
    """Export the process tracer to its configured path (or ``default``);
    None when tracing never enabled. The CLIs call this at run end."""
    if not TRACER.enabled and not len(TRACER):
        return None
    return TRACER.write(TRACER.default_out or default)


__all__ = [
    "TRACER",
    "Tracer",
    "enabled",
    "ensure_configured",
    "instant",
    "new_sweep_id",
    "span",
    "write_configured",
]
