"""Weight-manifest checksums: the detection half of the integrity layer.

One ``integrity.json`` per prepared model dir, written atomically
(tmp + rename) by every checkpoint writer, keyed by layer name:

.. code-block:: json

    {
      "version": 1,
      "algorithm": "crc32",
      "layers": {
        "model.layers.0": {
          "file": "model.layers.0.safetensors",
          "tensors": {"attn.wq": {"c": "9a3f01b2", "n": 16384}, ...}
        }
      }
    }

``c`` is the crc32 (hex) of the tensor's raw stored bytes — exactly the
contiguous little-endian payload safetensors serializes, so verification
reads the same bytes the mmap loader hands to ``device_put``. ``n`` is
the byte count (catches truncation before the checksum pass even runs).
crc32 (zlib, always available) rather than a cryptographic hash on
purpose: the threat model is *accidental* corruption — media/bus/page-
cache bit-flips and torn writes — not an adversary, and the stream reads
GBs per sweep, so the checksum must be cheap. The ``algorithm`` field is
self-describing so a future xxhash/crc32c upgrade stays compatible.

Error taxonomy (consumed by ``runtime/executor.py`` and
``runtime/activations.py``):

- ``ChecksumMismatch`` — **an IOError, deliberately**: the retry layer
  (``faults/retry.py``) treats it like any transient read fault, because
  a re-read genuinely heals page-cache/NFS corruption. Only a mismatch
  that survives every re-read means the bytes on disk are wrong.
- ``ShardCorruptError`` — a weight shard's mismatch survived retry
  exhaustion; subclasses ``ShardLoadError`` so the serving engine's
  degrade path (wave-fail + source restart) applies unchanged. The
  loader quarantines the file path: further loads fail fast instead of
  re-paying the full retry ladder per sweep.
- ``SpillCorruptError`` / ``SpillReadError`` — an activation spill is
  corrupt / unreadable even after re-reads. NOT an OSError: the healing
  action is recomputing the block from the last good shard boundary
  (disk mode's generation ping-pong keeps the inputs intact), not
  another retry.
"""

from __future__ import annotations

import hashlib
import json
import os
import zlib

import numpy as np

from flexible_llm_sharding_tpu.faults.retry import ShardLoadError

MANIFEST_NAME = "integrity.json"
ALGORITHM = "crc32"


class ChecksumMismatch(IOError):
    """A stored tensor's bytes do not match the manifest. An ``IOError``
    on purpose — the retry policy re-reads (page-cache/NFS corruption
    heals on a re-read); persistence, not occurrence, escalates."""


class ShardCorruptError(ShardLoadError):
    """A weight shard's checksum mismatch survived every re-read: the
    bytes on disk are wrong. The loader quarantines the path (further
    loads of it fail fast). A ``ShardLoadError`` subclass, so existing
    degrade paths (serve wave-fail + source restart) apply unchanged."""


class SpillCorruptError(RuntimeError):
    """An activation spill failed verification even after re-reads. The
    executor recomputes the affected block from the last good shard
    boundary (disk mode) instead of crashing; where recompute is
    impossible the error carries the offending path and shard index."""


class SpillReadError(SpillCorruptError):
    """A spill file could not be read or decoded at all (truncated
    ``.npy``, I/O failure) — named by path and shard index instead of a
    bare numpy ValueError. Subclasses ``SpillCorruptError`` so the
    executor's recompute heals truncated spills too."""


def _raw_bytes(arr: np.ndarray) -> np.ndarray:
    """A tensor's stored payload as a flat uint8 view (zero-copy for
    contiguous inputs, including ml_dtypes extension types)."""
    a = np.ascontiguousarray(arr)
    if a.nbytes == 0:
        return np.empty(0, np.uint8)
    return a.reshape(-1).view(np.uint8)


def checksum_bytes(buf) -> str:
    return f"{zlib.crc32(buf) & 0xFFFFFFFF:08x}"


def tensor_checksum(arr: np.ndarray) -> str:
    """crc32 (hex) over a tensor's raw contiguous bytes — the single
    checksum primitive shared by the manifest, the spill sidecars, and
    the offline ``verify`` audit."""
    return checksum_bytes(_raw_bytes(arr))


def layer_entry(flat: dict[str, np.ndarray], file_name: str) -> dict:
    """Manifest entry for one layer file's flat tensor dict (as stored)."""
    return {
        "file": file_name,
        "tensors": {
            k: {"c": tensor_checksum(v), "n": int(np.asarray(v).nbytes)}
            for k, v in flat.items()
        },
    }


def write_manifest(out_dir: str, layers: dict[str, dict]) -> str:
    """Atomically write ``integrity.json`` (tmp + rename — a crash
    mid-write leaves the previous manifest intact, mirroring the resume
    marker contract). Returns the manifest path."""
    path = os.path.join(out_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {"version": 1, "algorithm": ALGORITHM, "layers": layers},
            f,
            sort_keys=True,
        )
    os.replace(tmp, path)
    return path


# (path -> ((mtime_ns, size), parsed)) parse cache: a streaming run builds
# one loader per executor call, and each would otherwise re-parse the same
# JSON — for a large model the manifest is O(100 KB). Keyed by stat, so a
# re-prepared dir (atomic rename = new mtime) always re-reads. Entries are
# never evicted: processes touch a handful of model dirs.
_MANIFEST_CACHE: dict[str, tuple[tuple[int, int], dict]] = {}


def load_manifest(model_dir: str) -> dict | None:
    """The dir's manifest, or None when absent (old prepared dirs load
    with a one-time warning — back-compat). A *corrupt* manifest raises:
    writes are atomic, so torn JSON here is itself evidence of the
    corruption this layer exists to catch."""
    path = os.path.join(model_dir, MANIFEST_NAME)
    try:
        st = os.stat(path)
    except OSError:
        return None
    key = (st.st_mtime_ns, st.st_size)
    hit = _MANIFEST_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    with open(path) as f:
        try:
            data = json.load(f)
        except ValueError as e:
            raise ValueError(
                f"{path}: integrity manifest is corrupt JSON ({e}); "
                "re-prepare the model dir or delete the manifest to load "
                "unverified"
            ) from e
    if not isinstance(data.get("layers"), dict):
        raise ValueError(f"{path}: integrity manifest has no 'layers' map")
    _MANIFEST_CACHE[path] = (key, data)
    return data


def manifest_digest(manifest: dict | None) -> str:
    """Stable hash of a manifest ("" when absent) — folded into the
    resume workload signature and recorded in progress markers so a
    resumed run can never trust spills produced against different
    weights."""
    if manifest is None:
        return ""
    return hashlib.sha1(
        json.dumps(manifest, sort_keys=True).encode()
    ).hexdigest()


def verify_flat(
    layer_name: str,
    flat: dict[str, np.ndarray],
    manifest: dict,
    path: str = "",
) -> None:
    """Verify one loaded layer's flat tensors against the manifest.

    Raises ``ChecksumMismatch`` (retryable — see module docstring) naming
    the file, tensor, and expected/got values. A layer absent from the
    manifest verifies vacuously on the load path (structural drift is the
    offline ``verify`` audit's job, where it fails with a precise diff).
    """
    entry = manifest.get("layers", {}).get(layer_name)
    if entry is None:
        return
    where = path or layer_name
    want = entry.get("tensors", {})
    missing = want.keys() - flat.keys()
    if missing:
        raise ChecksumMismatch(
            f"{where}: tensors {sorted(missing)} listed in the integrity "
            "manifest are absent from the file"
        )
    extra = flat.keys() - want.keys()
    if extra:
        raise ChecksumMismatch(
            f"{where}: tensors {sorted(extra)} present in the file but not "
            "in the integrity manifest"
        )
    for key, meta in want.items():
        arr = np.asarray(flat[key])
        if int(arr.nbytes) != int(meta["n"]):
            raise ChecksumMismatch(
                f"{where}: tensor {key!r} has {arr.nbytes} bytes, manifest "
                f"records {meta['n']} (truncated/resized payload)"
            )
        got = tensor_checksum(arr)
        if got != meta["c"]:
            raise ChecksumMismatch(
                f"{where}: tensor {key!r} checksum {got} != manifest "
                f"{meta['c']} (corrupt bytes)"
            )


# -- spill sidecars ---------------------------------------------------------
# One tiny text sidecar per .npy activation spill: "crc32:<hex>:<nbytes>".
# Written atomically after the .npy lands; absent on files from older runs
# (those load unverified — back-compat).

SIDECAR_SUFFIX = ".crc"


def write_sidecar(npy_path: str, arr: np.ndarray) -> None:
    tmp = npy_path + SIDECAR_SUFFIX + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{ALGORITHM}:{tensor_checksum(arr)}:{int(arr.nbytes)}\n")
    os.replace(tmp, npy_path + SIDECAR_SUFFIX)


def read_sidecar(npy_path: str) -> tuple[str, int] | None:
    """(checksum, nbytes) recorded for a spill, or None when the sidecar
    is absent (legacy spill — unverified). A malformed sidecar reads as a
    mismatch sentinel ("", -1): sidecar corruption is corruption."""
    try:
        with open(npy_path + SIDECAR_SUFFIX) as f:
            algo, csum, nbytes = f.read().strip().split(":")
        if algo != ALGORITHM:
            return ("", -1)
        return (csum, int(nbytes))
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        return ("", -1)


def remove_sidecar(npy_path: str) -> None:
    try:
        os.remove(npy_path + SIDECAR_SUFFIX)
    except OSError:
        pass


__all__ = [
    "ALGORITHM",
    "MANIFEST_NAME",
    "SIDECAR_SUFFIX",
    "ChecksumMismatch",
    "ShardCorruptError",
    "SpillCorruptError",
    "SpillReadError",
    "checksum_bytes",
    "layer_entry",
    "load_manifest",
    "manifest_digest",
    "read_sidecar",
    "remove_sidecar",
    "tensor_checksum",
    "verify_flat",
    "write_manifest",
    "write_sidecar",
]
