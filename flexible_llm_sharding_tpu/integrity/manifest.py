"""Weight-manifest checksums: the detection half of the integrity layer.

One ``integrity.json`` per prepared model dir, written atomically
(tmp + rename) by every checkpoint writer, keyed by layer name:

.. code-block:: json

    {
      "version": 1,
      "algorithm": "crc32",
      "layers": {
        "model.layers.0": {
          "file": "model.layers.0.safetensors",
          "tensors": {"attn.wq": {"c": "9a3f01b2", "n": 16384}, ...}
        }
      }
    }

``c`` is the crc32 (hex) of the tensor's raw stored bytes — exactly the
contiguous little-endian payload safetensors serializes, so verification
reads the same bytes the mmap loader hands to ``device_put``. ``n`` is
the byte count (catches truncation before the checksum pass even runs).
crc32 (zlib, always available) rather than a cryptographic hash on
purpose: the threat model is *accidental* corruption — media/bus/page-
cache bit-flips and torn writes — not an adversary, and the stream reads
GBs per sweep, so the checksum must be cheap. The ``algorithm`` field is
self-describing so a future xxhash/crc32c upgrade stays compatible.

Error taxonomy (consumed by ``runtime/executor.py`` and
``runtime/activations.py``):

- ``ChecksumMismatch`` — **an IOError, deliberately**: the retry layer
  (``faults/retry.py``) treats it like any transient read fault, because
  a re-read genuinely heals page-cache/NFS corruption. Only a mismatch
  that survives every re-read means the bytes on disk are wrong.
- ``ShardCorruptError`` — a weight shard's mismatch survived retry
  exhaustion; subclasses ``ShardLoadError`` so the serving engine's
  degrade path (wave-fail + source restart) applies unchanged. The
  loader quarantines the file path: further loads fail fast instead of
  re-paying the full retry ladder per sweep.
- ``SpillCorruptError`` / ``SpillReadError`` — an activation spill is
  corrupt / unreadable even after re-reads. NOT an OSError: the healing
  action is recomputing the block from the last good shard boundary
  (disk mode's generation ping-pong keeps the inputs intact), not
  another retry.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib

import numpy as np

from flexible_llm_sharding_tpu.faults.retry import ShardLoadError

MANIFEST_NAME = "integrity.json"
ALGORITHM = "crc32"


class ChecksumMismatch(IOError):
    """A stored tensor's bytes do not match the manifest. An ``IOError``
    on purpose — the retry policy re-reads (page-cache/NFS corruption
    heals on a re-read); persistence, not occurrence, escalates."""


class ShardCorruptError(ShardLoadError):
    """A weight shard's checksum mismatch survived every re-read: the
    bytes on disk are wrong. The loader quarantines the path (further
    loads of it fail fast). A ``ShardLoadError`` subclass, so existing
    degrade paths (serve wave-fail + source restart) apply unchanged."""


class SpillCorruptError(RuntimeError):
    """An activation spill failed verification even after re-reads. The
    executor recomputes the affected block from the last good shard
    boundary (disk mode) instead of crashing; where recompute is
    impossible the error carries the offending path and shard index."""


class SpillReadError(SpillCorruptError):
    """A spill file could not be read or decoded at all (truncated
    ``.npy``, I/O failure) — named by path and shard index instead of a
    bare numpy ValueError. Subclasses ``SpillCorruptError`` so the
    executor's recompute heals truncated spills too."""


class PrecisionMismatch(ShardLoadError):
    """A layer file's actual storage dtype disagrees with what the
    integrity manifest (or the checkpoint's embedded ``PrecisionPlan``)
    declares for it — e.g. an int4 file swapped in where the manifest
    records bf16. STRUCTURAL, not transient: a re-read returns the same
    wrong dtype, so this is deliberately NOT an ``OSError`` (the retry
    ladder must not triple its latency) — but it IS a ``ShardLoadError``,
    so the serving degrade path (wave-fail + source restart) applies
    unchanged while the message names the layer and both dtypes."""


def _raw_bytes(arr: np.ndarray) -> np.ndarray:
    """A tensor's stored payload as a flat uint8 view (zero-copy for
    contiguous inputs, including ml_dtypes extension types)."""
    a = np.ascontiguousarray(arr)
    if a.nbytes == 0:
        return np.empty(0, np.uint8)
    return a.reshape(-1).view(np.uint8)


def checksum_bytes(buf) -> str:
    return f"{zlib.crc32(buf) & 0xFFFFFFFF:08x}"


# Chunk size for the incremental crc pass. Chunking (rather than one
# monolithic zlib call over a GB-scale mmap view) keeps the hash walking
# the bytes in page-cache-friendly strides: each chunk's pages fault in,
# get hashed while hot, and the kernel's readahead stays ahead of the
# hasher — the hash rides the same read the loader is doing anyway
# instead of forcing a second full-buffer pass pattern.
_CRC_CHUNK = 4 << 20


def checksum_chunked(flat_u8: np.ndarray, chunk: int = _CRC_CHUNK) -> str:
    """Incremental crc32 over a flat uint8 array, ``chunk`` bytes at a
    time — the shared chunked-hash reader used by the weight-manifest
    verify pass and the activation-spill sidecar checks."""
    crc = 0
    n = flat_u8.nbytes
    for off in range(0, n, chunk):
        crc = zlib.crc32(flat_u8[off : off + chunk], crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def tensor_checksum(arr: np.ndarray) -> str:
    """crc32 (hex) over a tensor's raw contiguous bytes — the single
    checksum primitive shared by the manifest, the spill sidecars, and
    the offline ``verify`` audit. Computed chunked (see
    :func:`checksum_chunked`) so hashing a large mmap view streams its
    pages instead of demanding the whole buffer at once."""
    return checksum_chunked(_raw_bytes(arr))


def layer_entry(flat: dict[str, np.ndarray], file_name: str) -> dict:
    """Manifest entry for one layer file's flat tensor dict (as stored).

    ``dtype`` records the layer's storage-dtype kind (int4/int8/bfloat16/
    float32 — ``checkpoint.flat_dtype_kind``, the ONE derivation shared
    with the load-path check), so a file whose precision silently
    disagrees with the manifest (a uniform-int4 file swapped into a
    mixed-precision dir's bf16 slot) is a typed ``PrecisionMismatch`` at
    load time, not a quality regression discovered in production.
    Entries written before this field load unchecked (back-compat)."""
    # Function-level import: checkpoint.py imports this module at module
    # scope; by the time any writer calls layer_entry the checkpoint
    # module is importable, so the kind derivation stays single-sourced.
    from flexible_llm_sharding_tpu.utils.checkpoint import flat_dtype_kind

    return {
        "file": file_name,
        "dtype": flat_dtype_kind(flat),
        "tensors": {
            k: {"c": tensor_checksum(v), "n": int(np.asarray(v).nbytes)}
            for k, v in flat.items()
        },
    }


def write_manifest(out_dir: str, layers: dict[str, dict]) -> str:
    """Atomically write ``integrity.json`` (tmp + rename — a crash
    mid-write leaves the previous manifest intact, mirroring the resume
    marker contract). Returns the manifest path."""
    path = os.path.join(out_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {"version": 1, "algorithm": ALGORITHM, "layers": layers},
            f,
            sort_keys=True,
        )
    os.replace(tmp, path)
    return path


# (path -> ((mtime_ns, size), parsed)) parse cache: a streaming run builds
# one loader per executor call, and each would otherwise re-parse the same
# JSON — for a large model the manifest is O(100 KB). Keyed by stat, so a
# re-prepared dir (atomic rename = new mtime) always re-reads. Entries are
# never evicted: processes touch a handful of model dirs.
_MANIFEST_CACHE: dict[str, tuple[tuple[int, int], dict]] = {}


def load_manifest(model_dir: str) -> dict | None:
    """The dir's manifest, or None when absent (old prepared dirs load
    with a one-time warning — back-compat). A *corrupt* manifest raises:
    writes are atomic, so torn JSON here is itself evidence of the
    corruption this layer exists to catch."""
    path = os.path.join(model_dir, MANIFEST_NAME)
    try:
        st = os.stat(path)
    except OSError:
        return None
    key = (st.st_mtime_ns, st.st_size)
    hit = _MANIFEST_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    with open(path) as f:
        try:
            data = json.load(f)
        except ValueError as e:
            raise ValueError(
                f"{path}: integrity manifest is corrupt JSON ({e}); "
                "re-prepare the model dir or delete the manifest to load "
                "unverified"
            ) from e
    if not isinstance(data.get("layers"), dict):
        raise ValueError(f"{path}: integrity manifest has no 'layers' map")
    _MANIFEST_CACHE[path] = (key, data)
    return data


def manifest_digest(manifest: dict | None) -> str:
    """Stable hash of a manifest ("" when absent) — folded into the
    resume workload signature and recorded in progress markers so a
    resumed run can never trust spills produced against different
    weights."""
    if manifest is None:
        return ""
    return hashlib.sha1(
        json.dumps(manifest, sort_keys=True).encode()
    ).hexdigest()


# -- crc verdict cache -------------------------------------------------------
# One crc pass per FILE GENERATION instead of one per sweep: the streaming
# regime re-reads every layer file once per full-model sweep (and the serve
# loop sweeps indefinitely), but the bytes only change when the file does.
# A verdict is recorded ONLY after a full verify_flat pass succeeded, keyed
# by the layer file's (path, mtime_ns, size) AND the manifest file's own
# (mtime_ns, size) — so a repaired shard, an in-place re-prepare, on-disk
# rot (any write updates mtime), or a regenerated manifest each invalidate
# automatically. Failures are never cached: a mismatch re-verifies on every
# re-read, exactly as the heal/quarantine ladder requires. Chaos-injected
# in-memory corruption bypasses the cache entirely (utils/checkpoint.py
# only consults it when the injector did not fire), so seeded fault
# schedules keep their per-load detection semantics.

_VERDICT_CACHE: dict[tuple, tuple] = {}
_VERDICT_LOCK = threading.Lock()
_VERDICT_STATS = {"verdict_hits": 0, "full_verifies": 0}


def _file_key(path: str) -> tuple[int, int] | None:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size)


def _verdict_key(model_dir: str, file_path: str) -> tuple | None:
    fk = _file_key(file_path)
    mk = _file_key(os.path.join(model_dir, MANIFEST_NAME))
    if fk is None or mk is None:
        return None
    return (file_path, mk)


def verdict_token(model_dir: str, file_path: str):
    """Capture the verdict identity of ``file_path`` — (cache key, file
    stat) — or None when either file can't be stat'ed. Callers take this
    BEFORE reading the bytes they are about to verify and hand it back to
    :func:`record_verdict`: the verdict then binds to the generation
    actually read, so a concurrent atomic replacement cannot earn the NEW
    file a clean verdict from the OLD file's bytes (the stale token's
    stat no longer matches and the next load re-verifies)."""
    key = _verdict_key(model_dir, file_path)
    fk = _file_key(file_path)
    if key is None or fk is None:
        return None
    return (key, fk)


def verdict_cached(token) -> bool:
    """True when the file generation ``token`` describes already passed a
    full verify against the dir's manifest (counted as a verdict hit)."""
    if token is None:
        return False
    key, fk = token
    with _VERDICT_LOCK:
        hit = _VERDICT_CACHE.get(key) == fk
        if hit:
            _VERDICT_STATS["verdict_hits"] += 1
        return hit


def record_verdict(token) -> None:
    """Record a clean full-verify for the pre-read ``token``."""
    if token is None:
        return
    key, fk = token
    with _VERDICT_LOCK:
        _VERDICT_CACHE[key] = fk


def invalidate_verdict(file_path: str) -> None:
    """Drop any cached verdicts for ``file_path`` (the loader's quarantine
    hook — a quarantined path must re-verify from scratch after repair)."""
    with _VERDICT_LOCK:
        for key in [k for k in _VERDICT_CACHE if k[0] == file_path]:
            del _VERDICT_CACHE[key]


def count_full_verify() -> None:
    with _VERDICT_LOCK:
        _VERDICT_STATS["full_verifies"] += 1


def verdict_stats() -> dict[str, int]:
    """Process-wide hash-amortization counters: ``verdict_hits`` (loads
    that skipped the crc pass on a cached clean verdict) and
    ``full_verifies`` (full verify_flat passes actually run). Executors
    snapshot deltas into their stats; the serve stats line carries them."""
    with _VERDICT_LOCK:
        return dict(_VERDICT_STATS)


def reset_verdict_stats() -> None:
    with _VERDICT_LOCK:
        _VERDICT_STATS["verdict_hits"] = 0
        _VERDICT_STATS["full_verifies"] = 0


def reset_verdicts() -> None:
    """Drop every cached verdict AND zero the counters (tests)."""
    with _VERDICT_LOCK:
        _VERDICT_CACHE.clear()
        _VERDICT_STATS["verdict_hits"] = 0
        _VERDICT_STATS["full_verifies"] = 0


def verify_flat(
    layer_name: str,
    flat: dict[str, np.ndarray],
    manifest: dict,
    path: str = "",
) -> None:
    """Verify one loaded layer's flat tensors against the manifest.

    Raises ``ChecksumMismatch`` (retryable — see module docstring) naming
    the file, tensor, and expected/got values. A layer absent from the
    manifest verifies vacuously on the load path (structural drift is the
    offline ``verify`` audit's job, where it fails with a precise diff).
    """
    count_full_verify()
    entry = manifest.get("layers", {}).get(layer_name)
    if entry is None:
        return
    where = path or layer_name
    want = entry.get("tensors", {})
    missing = want.keys() - flat.keys()
    if missing:
        raise ChecksumMismatch(
            f"{where}: tensors {sorted(missing)} listed in the integrity "
            "manifest are absent from the file"
        )
    extra = flat.keys() - want.keys()
    if extra:
        raise ChecksumMismatch(
            f"{where}: tensors {sorted(extra)} present in the file but not "
            "in the integrity manifest"
        )
    for key, meta in want.items():
        arr = np.asarray(flat[key])
        if int(arr.nbytes) != int(meta["n"]):
            raise ChecksumMismatch(
                f"{where}: tensor {key!r} has {arr.nbytes} bytes, manifest "
                f"records {meta['n']} (truncated/resized payload)"
            )
        got = tensor_checksum(arr)
        if got != meta["c"]:
            raise ChecksumMismatch(
                f"{where}: tensor {key!r} checksum {got} != manifest "
                f"{meta['c']} (corrupt bytes)"
            )


# -- spill sidecars ---------------------------------------------------------
# One tiny text sidecar per .npy activation spill: "crc32:<hex>:<nbytes>".
# Written atomically after the .npy lands; absent on files from older runs
# (those load unverified — back-compat).

SIDECAR_SUFFIX = ".crc"


def write_sidecar(npy_path: str, arr: np.ndarray) -> None:
    tmp = npy_path + SIDECAR_SUFFIX + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{ALGORITHM}:{tensor_checksum(arr)}:{int(arr.nbytes)}\n")
    os.replace(tmp, npy_path + SIDECAR_SUFFIX)


def read_sidecar(npy_path: str) -> tuple[str, int] | None:
    """(checksum, nbytes) recorded for a spill, or None when the sidecar
    is absent (legacy spill — unverified). A malformed sidecar reads as a
    mismatch sentinel ("", -1): sidecar corruption is corruption."""
    try:
        with open(npy_path + SIDECAR_SUFFIX) as f:
            algo, csum, nbytes = f.read().strip().split(":")
        if algo != ALGORITHM:
            return ("", -1)
        return (csum, int(nbytes))
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        return ("", -1)


def remove_sidecar(npy_path: str) -> None:
    try:
        os.remove(npy_path + SIDECAR_SUFFIX)
    except OSError:
        pass


__all__ = [
    "ALGORITHM",
    "MANIFEST_NAME",
    "SIDECAR_SUFFIX",
    "ChecksumMismatch",
    "PrecisionMismatch",
    "ShardCorruptError",
    "SpillCorruptError",
    "SpillReadError",
    "checksum_bytes",
    "checksum_chunked",
    "invalidate_verdict",
    "layer_entry",
    "load_manifest",
    "manifest_digest",
    "read_sidecar",
    "record_verdict",
    "remove_sidecar",
    "reset_verdict_stats",
    "tensor_checksum",
    "verdict_cached",
    "verdict_stats",
    "verdict_token",
    "verify_flat",
    "write_manifest",
    "write_sidecar",
]
