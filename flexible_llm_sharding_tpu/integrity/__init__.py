"""End-to-end integrity for the streamed-bytes path.

The framework's whole design pushes ~the full model through the chip from
host storage every sweep and spills activations to RAM/disk between
shards — and until this package every byte on that path was trusted
blindly. PR 3 (``faults/``) made *transient* I/O errors survivable; this
package catches and heals *silent corruption*: a bit-flip in a prepared
``.safetensors`` shard, a truncated ``.npy`` spill, a stale spill picked
up by a disk-mode resume.

- ``manifest`` — per-layer content checksums (crc32 over raw tensor
  bytes) written atomically next to the layer files by the checkpoint
  writers (``utils/checkpoint.py``), verified on every load by
  ``_HostShardLoader`` (``runtime/executor.py``). A mismatch is
  *retryable* (a re-read heals page-cache/NFS corruption); only
  persistent mismatches escalate to a typed ``ShardCorruptError`` that
  quarantines the shard path. Spill files (``runtime/activations.py``)
  get one checksum sidecar per ``.npy``; a persistent spill mismatch
  makes the executor *recompute* the affected block from the last good
  shard boundary instead of crashing.
- ``verify`` — an offline audit (the ``verify`` CLI subcommand) of a
  prepared model dir and/or spill dir: recomputes every checksum and
  reports per-file mismatches, manifest/dir structural drift, and
  unreadable files; exits nonzero on any finding.

Counters (``integrity_failures`` / ``reread_heals`` / ``recomputes`` /
``quarantined_shards``) flow through ``utils.metrics.IntegrityRecorder``
into executor stats and the serve stats line. Chaos coverage: the
``corrupt_shard`` / ``corrupt_activation`` fault sites (``faults/
inject.py``) deterministically bit-flip or truncate the streamed bytes,
and ``tests/test_integrity.py`` pins outputs token-identical to a
fault-free run. docs/integrity.md holds the threat model.
"""

from flexible_llm_sharding_tpu.integrity.manifest import (  # noqa: F401
    MANIFEST_NAME,
    ChecksumMismatch,
    ShardCorruptError,
    SpillCorruptError,
    SpillReadError,
    layer_entry,
    load_manifest,
    manifest_digest,
    tensor_checksum,
    verify_flat,
    write_manifest,
)

__all__ = [
    "MANIFEST_NAME",
    "ChecksumMismatch",
    "ShardCorruptError",
    "SpillCorruptError",
    "SpillReadError",
    "layer_entry",
    "load_manifest",
    "manifest_digest",
    "tensor_checksum",
    "verify_flat",
    "write_manifest",
]
