"""Offline integrity audit — the ``verify`` CLI subcommand's engine.

Recomputes every checksum in a prepared model dir (against its
``integrity.json``) and/or a spill dir (against the per-``.npy``
sidecars) and returns a structured per-file report. Unlike the load
path — which tolerates a layer missing from the manifest so old
prepared dirs keep loading — the audit is STRICT: manifest/dir drift
(a layer in the manifest but not on disk, a layer file the manifest
never heard of, tensor-set differences) fails with a precise diff.

Pure host-side numpy: no JAX import, so it runs anywhere the files do.
"""

from __future__ import annotations

import os

import numpy as np

from flexible_llm_sharding_tpu.integrity import manifest as iman


def _problem(file: str, status: str, detail: str = "") -> dict:
    return {"file": file, "status": status, "detail": detail}


def verify_model_dir(model_dir: str) -> dict:
    """Audit a prepared per-layer checkpoint dir.

    Returns ``{"path", "ok", "layers_checked", "tensors_checked",
    "problems": [{"file", "status", "detail"}, ...]}``. Statuses:
    ``no_manifest`` | ``corrupt_manifest`` | ``missing_file`` |
    ``not_in_manifest`` | ``unreadable`` | ``tensor_diff`` |
    ``mismatch``.
    """
    # Function-level import (like _mmap_safetensors) keeps verify_spill_dir
    # usable without the checkpoint module's heavier deps.
    from flexible_llm_sharding_tpu.utils.checkpoint import (
        LAYER_FILE_SUFFIX as _LAYER_SUFFIX,
    )
    from flexible_llm_sharding_tpu.utils.checkpoint import _mmap_safetensors

    problems: list[dict] = []
    layers_checked = tensors_checked = 0
    try:
        manifest = iman.load_manifest(model_dir)
    except ValueError as e:
        manifest = None
        problems.append(_problem(iman.MANIFEST_NAME, "corrupt_manifest", str(e)))
    else:
        if manifest is None:
            problems.append(
                _problem(
                    iman.MANIFEST_NAME,
                    "no_manifest",
                    "dir has no integrity manifest; re-prepare (or re-save) "
                    "to enable verification",
                )
            )
    man_layers = dict((manifest or {}).get("layers", {}))
    disk_layers = {
        f[: -len(_LAYER_SUFFIX)]
        for f in os.listdir(model_dir)
        if f.endswith(_LAYER_SUFFIX)
    }
    for layer in sorted(man_layers.keys() - disk_layers):
        problems.append(
            _problem(
                man_layers[layer].get("file", layer + _LAYER_SUFFIX),
                "missing_file",
                f"layer {layer!r} is in the manifest but its file is gone",
            )
        )
    for layer in sorted(disk_layers - man_layers.keys()):
        if manifest is not None:
            problems.append(
                _problem(
                    layer + _LAYER_SUFFIX,
                    "not_in_manifest",
                    f"layer file {layer!r} exists on disk but the manifest "
                    "has no entry for it",
                )
            )
    for layer in sorted(man_layers.keys() & disk_layers):
        fname = layer + _LAYER_SUFFIX
        path = os.path.join(model_dir, fname)
        try:
            flat = _mmap_safetensors(path)
        except Exception as e:  # truncated header, bad magic, ...
            problems.append(_problem(fname, "unreadable", repr(e)))
            continue
        layers_checked += 1
        want = man_layers[layer].get("tensors", {})
        missing = sorted(want.keys() - flat.keys())
        extra = sorted(flat.keys() - want.keys())
        if missing or extra:
            problems.append(
                _problem(
                    fname,
                    "tensor_diff",
                    f"manifest-only tensors {missing}, file-only tensors "
                    f"{extra}",
                )
            )
        for key in sorted(want.keys() & flat.keys()):
            tensors_checked += 1
            arr = np.asarray(flat[key])
            meta = want[key]
            if int(arr.nbytes) != int(meta["n"]):
                problems.append(
                    _problem(
                        fname,
                        "mismatch",
                        f"tensor {key!r}: {arr.nbytes} bytes vs manifest "
                        f"{meta['n']} (truncated/resized)",
                    )
                )
                continue
            got = iman.tensor_checksum(arr)
            if got != meta["c"]:
                problems.append(
                    _problem(
                        fname,
                        "mismatch",
                        f"tensor {key!r}: checksum {got} != manifest "
                        f"{meta['c']}",
                    )
                )
    return {
        "path": model_dir,
        "ok": not problems,
        "layers_checked": layers_checked,
        "tensors_checked": tensors_checked,
        "problems": problems,
    }


def verify_spill_dir(spill_dir: str) -> dict:
    """Audit an activation spill dir: every ``.npy`` against its checksum
    sidecar. Spills without a sidecar (legacy runs) count as
    ``unverified`` — reported, but not a failure. Orphan sidecars
    (spill file gone) and unreadable/mismatching spills are failures.
    """
    problems: list[dict] = []
    checked = unverified = 0
    names = sorted(os.listdir(spill_dir))
    npys = [f for f in names if f.endswith(".npy")]
    for f in names:
        if f.endswith(".npy" + iman.SIDECAR_SUFFIX):
            if f[: -len(iman.SIDECAR_SUFFIX)] not in npys:
                problems.append(
                    _problem(f, "orphan_sidecar", "spill file is gone")
                )
    for f in npys:
        path = os.path.join(spill_dir, f)
        side = iman.read_sidecar(path)
        if side is None:
            unverified += 1
            continue
        try:
            arr = np.load(path)
        except Exception as e:  # truncated / undecodable
            problems.append(_problem(f, "unreadable", repr(e)))
            continue
        checked += 1
        csum, nbytes = side
        if int(arr.nbytes) != nbytes:
            problems.append(
                _problem(
                    f,
                    "mismatch",
                    f"{arr.nbytes} bytes vs sidecar {nbytes} (truncated)",
                )
            )
            continue
        got = iman.tensor_checksum(arr)
        if got != csum:
            problems.append(
                _problem(f, "mismatch", f"checksum {got} != sidecar {csum}")
            )
    return {
        "path": spill_dir,
        "ok": not problems,
        "spills_checked": checked,
        "spills_unverified": unverified,
        "problems": problems,
    }


def format_report(report: dict) -> str:
    """Human-readable per-file lines + one summary line."""
    lines = []
    for p in report["problems"]:
        lines.append(f"{p['status'].upper():>15}  {p['file']}  {p['detail']}")
    counted = ", ".join(
        f"{v} {k.replace('_', ' ')}"
        for k, v in report.items()
        if k.endswith(("_checked", "_unverified")) and v
    )
    verdict = "OK" if report["ok"] else f"{len(report['problems'])} problem(s)"
    lines.append(f"{report['path']}: {verdict}" + (f" ({counted})" if counted else ""))
    return "\n".join(lines)


__all__ = ["verify_model_dir", "verify_spill_dir", "format_report"]
