"""Offline integrity audit — the ``verify`` CLI subcommand's engine.

Recomputes every checksum in a prepared model dir (against its
``integrity.json``) and/or a spill dir (against the per-``.npy``
sidecars) and returns a structured per-file report. Unlike the load
path — which tolerates a layer missing from the manifest so old
prepared dirs keep loading — the audit is STRICT: manifest/dir drift
(a layer in the manifest but not on disk, a layer file the manifest
never heard of, tensor-set differences) fails with a precise diff.

Pure host-side numpy: no JAX import, so it runs anywhere the files do.
"""

from __future__ import annotations

import os

import numpy as np

from flexible_llm_sharding_tpu.integrity import manifest as iman


def _problem(file: str, status: str, detail: str = "") -> dict:
    return {"file": file, "status": status, "detail": detail}


def verify_model_dir(model_dir: str) -> dict:
    """Audit a prepared per-layer checkpoint dir.

    Returns ``{"path", "ok", "layers_checked", "tensors_checked",
    "problems": [{"file", "status", "detail"}, ...]}``. Statuses:
    ``no_manifest`` | ``corrupt_manifest`` | ``missing_file`` |
    ``not_in_manifest`` | ``unreadable`` | ``tensor_diff`` |
    ``mismatch`` — plus, for mixed-precision dirs with an embedded
    ``precision_plan.json``: ``corrupt_plan`` | ``precision_mismatch`` |
    ``plan_missing_file`` | ``not_in_plan`` (each layer's actual stored
    dtype kind and its manifest-recorded kind audited against the plan).
    """
    # Function-level import (like _mmap_safetensors) keeps verify_spill_dir
    # usable without the checkpoint module's heavier deps.
    from flexible_llm_sharding_tpu.utils.checkpoint import (
        LAYER_FILE_SUFFIX as _LAYER_SUFFIX,
    )
    from flexible_llm_sharding_tpu.utils.checkpoint import _mmap_safetensors

    problems: list[dict] = []
    layers_checked = tensors_checked = 0
    try:
        manifest = iman.load_manifest(model_dir)
    except ValueError as e:
        manifest = None
        problems.append(_problem(iman.MANIFEST_NAME, "corrupt_manifest", str(e)))
    else:
        if manifest is None:
            problems.append(
                _problem(
                    iman.MANIFEST_NAME,
                    "no_manifest",
                    "dir has no integrity manifest; re-prepare (or re-save) "
                    "to enable verification",
                )
            )
    man_layers = dict((manifest or {}).get("layers", {}))
    disk_layers = {
        f[: -len(_LAYER_SUFFIX)]
        for f in os.listdir(model_dir)
        if f.endswith(_LAYER_SUFFIX)
    }
    for layer in sorted(man_layers.keys() - disk_layers):
        problems.append(
            _problem(
                man_layers[layer].get("file", layer + _LAYER_SUFFIX),
                "missing_file",
                f"layer {layer!r} is in the manifest but its file is gone",
            )
        )
    for layer in sorted(disk_layers - man_layers.keys()):
        if manifest is not None:
            problems.append(
                _problem(
                    layer + _LAYER_SUFFIX,
                    "not_in_manifest",
                    f"layer file {layer!r} exists on disk but the manifest "
                    "has no entry for it",
                )
            )
    plan, plan_problems = _load_plan(model_dir)
    problems.extend(plan_problems)
    if plan is not None:
        # Plan vs the manifest's RECORDED kinds — the same shared
        # comparison the loader raises PrecisionMismatch from
        # (precisionplan.plan_manifest_problems), reported here in full.
        from flexible_llm_sharding_tpu.runtime.precisionplan import (
            plan_manifest_problems,
        )

        for layer, detail in plan_manifest_problems(plan, manifest):
            problems.append(
                _problem(
                    layer + _LAYER_SUFFIX, "precision_mismatch", detail
                )
            )
    plan_layers_checked = 0
    for layer in sorted(man_layers.keys() & disk_layers):
        fname = layer + _LAYER_SUFFIX
        path = os.path.join(model_dir, fname)
        try:
            flat = _mmap_safetensors(path)
        except Exception as e:  # truncated header, bad magic, ...
            problems.append(_problem(fname, "unreadable", repr(e)))
            continue
        layers_checked += 1
        if plan is not None:
            plan_layers_checked += _check_plan_layer(
                plan, layer, fname, flat, problems
            )
        want = man_layers[layer].get("tensors", {})
        missing = sorted(want.keys() - flat.keys())
        extra = sorted(flat.keys() - want.keys())
        if missing or extra:
            problems.append(
                _problem(
                    fname,
                    "tensor_diff",
                    f"manifest-only tensors {missing}, file-only tensors "
                    f"{extra}",
                )
            )
        for key in sorted(want.keys() & flat.keys()):
            tensors_checked += 1
            arr = np.asarray(flat[key])
            meta = want[key]
            if int(arr.nbytes) != int(meta["n"]):
                problems.append(
                    _problem(
                        fname,
                        "mismatch",
                        f"tensor {key!r}: {arr.nbytes} bytes vs manifest "
                        f"{meta['n']} (truncated/resized)",
                    )
                )
                continue
            got = iman.tensor_checksum(arr)
            if got != meta["c"]:
                problems.append(
                    _problem(
                        fname,
                        "mismatch",
                        f"tensor {key!r}: checksum {got} != manifest "
                        f"{meta['c']}",
                    )
                )
    if plan is not None:
        # Coverage both ways: every planned layer must exist on disk and
        # every layer file must have a plan entry (requantize_native
        # enforces this at write time; drift after the fact is exactly
        # what the audit exists to catch).
        for layer in sorted(set(plan.dtypes) - disk_layers):
            problems.append(
                _problem(
                    layer + _LAYER_SUFFIX,
                    "plan_missing_file",
                    f"precision plan covers layer {layer!r} but its file "
                    "is gone",
                )
            )
        for layer in sorted(disk_layers - set(plan.dtypes)):
            problems.append(
                _problem(
                    layer + _LAYER_SUFFIX,
                    "not_in_plan",
                    f"layer file {layer!r} exists on disk but the "
                    "embedded precision plan has no entry for it",
                )
            )
    report = {
        "path": model_dir,
        "ok": not problems,
        "layers_checked": layers_checked,
        "tensors_checked": tensors_checked,
        "problems": problems,
    }
    if plan is not None:
        report["plan_layers_checked"] = plan_layers_checked
        report["plan_divergence_cap"] = plan.divergence_cap
    return report


def _load_plan(model_dir: str):
    """(PrecisionPlan | None, problems): the checkpoint's embedded
    mixed-precision plan, with a corrupt plan reported instead of
    raised (the audit must keep walking the rest of the dir)."""
    from flexible_llm_sharding_tpu.runtime.precisionplan import (
        PLAN_NAME,
        PrecisionPlan,
    )

    try:
        return PrecisionPlan.load(model_dir), []
    except ValueError as e:
        return None, [_problem(PLAN_NAME, "corrupt_plan", str(e))]
    except OSError as e:
        # The plan EXISTS but can't be read (EACCES, EIO): a failure,
        # never "uniform checkpoint" — skipping the plan audit silently
        # is the exact hole the audit exists to close.
        return None, [
            _problem(PLAN_NAME, "corrupt_plan", f"unreadable: {e}")
        ]


def _check_plan_layer(
    plan, layer: str, fname: str, flat, problems: list
) -> int:
    """Validate one layer's ACTUAL stored bytes against the embedded
    PrecisionPlan (the plan-vs-manifest half runs once up front through
    the shared ``precisionplan.plan_manifest_problems``). Returns 1 when
    the layer was plan-checked (0 when the plan does not cover it — the
    coverage pass reports that separately)."""
    from flexible_llm_sharding_tpu.runtime.precisionplan import (
        PLAN_KIND_ACCEPTS,
    )
    from flexible_llm_sharding_tpu.utils.checkpoint import flat_dtype_kind

    plan_dtype = plan.dtypes.get(layer)
    if plan_dtype is None:
        return 0
    accepted = PLAN_KIND_ACCEPTS.get(plan_dtype, ())
    got = flat_dtype_kind(flat)
    if got not in accepted:
        problems.append(
            _problem(
                fname,
                "precision_mismatch",
                f"layer {layer!r} stores dtype kind {got!r}; the embedded "
                f"plan declares {plan_dtype!r} (accepts {list(accepted)})",
            )
        )
    return 1


def verify_spill_dir(spill_dir: str) -> dict:
    """Audit an activation spill dir: every ``.npy`` against its checksum
    sidecar. Spills without a sidecar (legacy runs) count as
    ``unverified`` — reported, but not a failure. Orphan sidecars
    (spill file gone) and unreadable/mismatching spills are failures.
    """
    problems: list[dict] = []
    checked = unverified = 0
    names = sorted(os.listdir(spill_dir))
    npys = [f for f in names if f.endswith(".npy")]
    for f in names:
        if f.endswith(".npy" + iman.SIDECAR_SUFFIX):
            if f[: -len(iman.SIDECAR_SUFFIX)] not in npys:
                problems.append(
                    _problem(f, "orphan_sidecar", "spill file is gone")
                )
    for f in npys:
        path = os.path.join(spill_dir, f)
        side = iman.read_sidecar(path)
        if side is None:
            unverified += 1
            continue
        try:
            arr = np.load(path)
        except Exception as e:  # truncated / undecodable
            problems.append(_problem(f, "unreadable", repr(e)))
            continue
        checked += 1
        csum, nbytes = side
        if int(arr.nbytes) != nbytes:
            problems.append(
                _problem(
                    f,
                    "mismatch",
                    f"{arr.nbytes} bytes vs sidecar {nbytes} (truncated)",
                )
            )
            continue
        got = iman.tensor_checksum(arr)
        if got != csum:
            problems.append(
                _problem(f, "mismatch", f"checksum {got} != sidecar {csum}")
            )
    return {
        "path": spill_dir,
        "ok": not problems,
        "spills_checked": checked,
        "spills_unverified": unverified,
        "problems": problems,
    }


def verify_adapter_dir(root: str) -> dict:
    """Audit a LoRA adapter registry root (``--adapter_dir``): every
    adapter subdir's delta safetensors recomputed against its integrity
    manifest, plus plan <-> dir structural drift — strict, like the
    model-dir audit (the serving loader tolerates what it can heal; the
    audit reports everything).

    Returns ``{"path", "ok", "adapters_checked", "layers_checked",
    "tensors_checked", "problems"}``. Statuses: ``corrupt_plan`` (plan
    missing/undecodable for a dir that holds delta files) |
    ``plan_missing_file`` (planned layer's file gone) | ``not_in_plan``
    | ``adapter_mismatch`` (checksum/size/shape diverges from the
    manifest or plan — the offline face of the loader's typed
    AdapterCorruptError) | the manifest statuses shared with
    :func:`verify_model_dir` (``no_manifest`` | ``corrupt_manifest`` |
    ``missing_file`` | ``not_in_manifest`` | ``unreadable`` |
    ``tensor_diff``).
    """
    from flexible_llm_sharding_tpu.adapters.registry import (
        ADAPTER_PLAN_NAME,
        AdapterPlan,
    )
    from flexible_llm_sharding_tpu.utils.checkpoint import (
        LAYER_FILE_SUFFIX as _LAYER_SUFFIX,
    )
    from flexible_llm_sharding_tpu.utils.checkpoint import st_load_file

    problems: list[dict] = []
    adapters_checked = layers_checked = tensors_checked = 0
    try:
        entries = sorted(os.listdir(root))
    except OSError as e:
        return {
            "path": root,
            "ok": False,
            "adapters_checked": 0,
            "layers_checked": 0,
            "tensors_checked": 0,
            "problems": [_problem(root, "unreadable", repr(e))],
        }
    for name in entries:
        adir = os.path.join(root, name)
        if not os.path.isdir(adir):
            continue
        disk_layers = {
            f[: -len(_LAYER_SUFFIX)]
            for f in os.listdir(adir)
            if f.endswith(_LAYER_SUFFIX)
        }
        try:
            plan = AdapterPlan.load(adir)
        except (ValueError, OSError) as e:
            problems.append(
                _problem(f"{name}/{ADAPTER_PLAN_NAME}", "corrupt_plan", str(e))
            )
            plan = None
        else:
            if plan is None:
                if not disk_layers:
                    continue  # unrelated subdir, not an adapter
                problems.append(
                    _problem(
                        f"{name}/{ADAPTER_PLAN_NAME}",
                        "corrupt_plan",
                        f"dir holds {len(disk_layers)} delta file(s) but "
                        "no adapter plan; re-run prepare-adapter",
                    )
                )
        if plan is None and not disk_layers:
            continue
        adapters_checked += 1
        plan_ranks = dict(plan.layers) if plan is not None else {}
        for layer in sorted(plan_ranks.keys() - disk_layers):
            problems.append(
                _problem(
                    f"{name}/{layer}{_LAYER_SUFFIX}",
                    "plan_missing_file",
                    f"adapter plan covers layer {layer!r} but its delta "
                    "file is gone",
                )
            )
        for layer in sorted(disk_layers - plan_ranks.keys()):
            if plan is not None:
                problems.append(
                    _problem(
                        f"{name}/{layer}{_LAYER_SUFFIX}",
                        "not_in_plan",
                        f"delta file {layer!r} exists on disk but the "
                        "adapter plan has no entry for it",
                    )
                )
        try:
            manifest = iman.load_manifest(adir)
        except ValueError as e:
            manifest = None
            problems.append(
                _problem(
                    f"{name}/{iman.MANIFEST_NAME}", "corrupt_manifest", str(e)
                )
            )
        else:
            if manifest is None:
                problems.append(
                    _problem(
                        f"{name}/{iman.MANIFEST_NAME}",
                        "no_manifest",
                        "adapter dir has no integrity manifest; re-run "
                        "prepare-adapter to enable verification",
                    )
                )
        man_layers = dict((manifest or {}).get("layers", {}))
        for layer in sorted(man_layers.keys() - disk_layers):
            problems.append(
                _problem(
                    f"{name}/{layer}{_LAYER_SUFFIX}",
                    "missing_file",
                    f"layer {layer!r} is in the manifest but its file is "
                    "gone",
                )
            )
        for layer in sorted(disk_layers - man_layers.keys()):
            if manifest is not None:
                problems.append(
                    _problem(
                        f"{name}/{layer}{_LAYER_SUFFIX}",
                        "not_in_manifest",
                        f"delta file {layer!r} exists on disk but the "
                        "manifest has no entry for it",
                    )
                )
        for layer in sorted(disk_layers):
            fname = layer + _LAYER_SUFFIX
            ref = f"{name}/{fname}"
            try:
                flat = st_load_file(os.path.join(adir, fname))
            except Exception as e:  # truncated header, bad magic, ...
                problems.append(_problem(ref, "unreadable", repr(e)))
                continue
            layers_checked += 1
            if plan is not None and layer in plan_ranks:
                # Shape audit against the plan — the offline face of the
                # loader's AdapterCorruptError shape check.
                want_a = (plan.hidden_size, plan_ranks[layer])
                a = flat.get("lora_A")
                b = flat.get("lora_B")
                if a is not None and tuple(a.shape) != want_a:
                    problems.append(
                        _problem(
                            ref,
                            "adapter_mismatch",
                            f"lora_A shape {tuple(a.shape)} vs plan "
                            f"{want_a}",
                        )
                    )
                if b is not None and tuple(b.shape) != want_a[::-1]:
                    problems.append(
                        _problem(
                            ref,
                            "adapter_mismatch",
                            f"lora_B shape {tuple(b.shape)} vs plan "
                            f"{want_a[::-1]}",
                        )
                    )
            want = man_layers.get(layer, {}).get("tensors")
            if want is None:
                continue
            missing = sorted(want.keys() - flat.keys())
            extra = sorted(flat.keys() - want.keys())
            if missing or extra:
                problems.append(
                    _problem(
                        ref,
                        "tensor_diff",
                        f"manifest-only tensors {missing}, file-only "
                        f"tensors {extra}",
                    )
                )
            for key in sorted(want.keys() & flat.keys()):
                tensors_checked += 1
                arr = np.asarray(flat[key])
                meta = want[key]
                if int(arr.nbytes) != int(meta["n"]):
                    problems.append(
                        _problem(
                            ref,
                            "adapter_mismatch",
                            f"tensor {key!r}: {arr.nbytes} bytes vs "
                            f"manifest {meta['n']} (truncated/resized)",
                        )
                    )
                    continue
                got = iman.tensor_checksum(arr)
                if got != meta["c"]:
                    problems.append(
                        _problem(
                            ref,
                            "adapter_mismatch",
                            f"tensor {key!r}: checksum {got} != manifest "
                            f"{meta['c']}",
                        )
                    )
    return {
        "path": root,
        "ok": not problems,
        "adapters_checked": adapters_checked,
        "layers_checked": layers_checked,
        "tensors_checked": tensors_checked,
        "problems": problems,
    }


def format_report(report: dict) -> str:
    """Human-readable per-file lines + one summary line."""
    lines = []
    for p in report["problems"]:
        lines.append(f"{p['status'].upper():>15}  {p['file']}  {p['detail']}")
    counted = ", ".join(
        f"{v} {k.replace('_', ' ')}"
        for k, v in report.items()
        if k.endswith(("_checked", "_unverified")) and v
    )
    verdict = "OK" if report["ok"] else f"{len(report['problems'])} problem(s)"
    lines.append(f"{report['path']}: {verdict}" + (f" ({counted})" if counted else ""))
    return "\n".join(lines)


__all__ = [
    "verify_adapter_dir",
    "verify_model_dir",
    "verify_spill_dir",
    "format_report",
]
